"""Keras-tier engine: shape-inferring layer adapter.

Reference: ``DL/nn/keras/KerasLayer`` + ``InferShape``
(``DL/nn/abstractnn/InferShape.scala``) — every Keras-style layer knows its
output shape given an input shape, so users never spell out fan-in sizes.

TPU-native design: a ``KerasLayer`` is a *builder* around the core layer
zoo. ``build(input_shape)`` instantiates the underlying
:class:`bigdl_tpu.nn.module.Module` once the input shape is known
(``Sequential.add`` or functional ``layer(node)`` both trigger it); after
that the KerasLayer delegates ``init``/``forward`` straight to the inner
module, so parameter trees look exactly like hand-built core models.

Shapes are Keras-style: tuples WITHOUT the batch dimension, e.g.
``(channels, h, w)`` for NCHW image inputs.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

from bigdl_tpu.nn.graph import Node
from bigdl_tpu.nn.module import Context, Module

Shape = Tuple[int, ...]


def conv_output_length(input_len: int, filter_size: int, border_mode: str,
                       stride: int, dilation: int = 1) -> int:
    """Keras conv/pool length arithmetic ('valid' or 'same')."""
    if input_len is None:
        return None
    eff = filter_size + (filter_size - 1) * (dilation - 1)
    if border_mode == "same":
        out = input_len
    elif border_mode == "valid":
        out = input_len - eff + 1
    else:
        raise ValueError(f"unknown border_mode {border_mode!r}")
    return (out + stride - 1) // stride


def same_padding(filter_size: int, dilation: int = 1) -> int:
    """Symmetric pad amount approximating Keras 'same' (odd kernels exact)."""
    eff = filter_size + (filter_size - 1) * (dilation - 1)
    return (eff - 1) // 2


def same_pad_amounts(filter_size: int, dilation: int = 1) -> Tuple[int, int]:
    """Exact (lo, hi) pad for 'same' with stride 1 — asymmetric for even
    kernels (the extra zero goes on the high side, TF/Keras convention)."""
    eff = filter_size + (filter_size - 1) * (dilation - 1)
    return (eff - 1) // 2, eff // 2


class KerasLayer(Module):
    """Base for all Keras-style layers.

    Subclasses implement ``build(input_shape) -> Module`` and
    ``compute_output_shape(input_shape) -> shape``; everything else
    (delegation, shape bookkeeping, the functional-API ``__call__``) lives
    here.
    """

    def __init__(self, input_shape: Optional[Sequence[int]] = None, name: Optional[str] = None):
        super().__init__()
        self._input_shape: Optional[Shape] = tuple(input_shape) if input_shape else None
        self._output_shape: Optional[Shape] = None
        self._inner: Optional[Module] = None
        if name:
            self.set_name(name)

    # -- to be overridden --
    def build(self, input_shape: Shape) -> Module:
        raise NotImplementedError

    def compute_output_shape(self, input_shape: Shape) -> Shape:
        raise NotImplementedError

    # -- machinery --
    def ensure_built(self, input_shape: Optional[Shape] = None) -> "KerasLayer":
        if self._inner is not None:
            return self
        shape = input_shape if input_shape is not None else self._input_shape
        if shape is None:
            raise ValueError(
                f"{type(self).__name__} needs an input_shape (first layer of a "
                f"Sequential must pass input_shape=...)"
            )
        self._input_shape = tuple(shape) if not _is_multi(shape) else tuple(map(tuple, shape))
        self._inner = self.build(self._input_shape)
        self._output_shape = self.compute_output_shape(self._input_shape)
        return self

    @property
    def input_shape(self) -> Optional[Shape]:
        return self._input_shape

    def get_output_shape(self) -> Shape:
        if self._output_shape is None:
            raise ValueError(f"{type(self).__name__} is not built yet")
        return self._output_shape

    # delegate init/forward to the inner module at the SAME tree level so
    # param paths match an equivalently hand-built core model
    def init(self, rng):
        self.ensure_built()
        return self._inner.init(rng)

    def forward(self, ctx: Context, x):
        self.ensure_built()
        return self._inner.forward(ctx, x)

    def param_pspecs(self):
        self.ensure_built()
        return self._inner.param_pspecs()

    # -- functional API: layer(node) with shape propagation --
    def __call__(self, *nodes):
        nodes = [n for n in nodes]
        if len(nodes) == 1 and isinstance(nodes[0], (list, tuple)):
            nodes = list(nodes[0])
        in_nodes = []
        for n in nodes:
            if not isinstance(n, Node):
                raise TypeError(
                    f"Keras functional API wires nodes (from Input()); got {type(n).__name__}"
                )
            in_nodes.append(n)
        shapes = [getattr(n, "keras_shape", None) for n in in_nodes]
        if any(s is None for s in shapes):
            raise ValueError("upstream node has no shape; start from keras.Input(shape=...)")
        in_shape = shapes[0] if len(shapes) == 1 else tuple(shapes)
        self.ensure_built(in_shape)
        out = Node(self, in_nodes)
        out.keras_shape = self.get_output_shape()
        return out


def _is_multi(shape) -> bool:
    return bool(shape) and isinstance(shape[0], (tuple, list))


def Input(shape: Sequence[int], name: Optional[str] = None) -> Node:
    """Functional-API entry point (reference ``DL/nn/keras`` Input).

    Returns a graph :class:`Node` carrying ``keras_shape`` (batch dim
    excluded) for downstream shape inference.
    """
    node = Node(None, [])
    node.keras_shape = tuple(shape)
    if name:
        node.name = name
    return node
