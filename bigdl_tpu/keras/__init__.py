"""Keras-1.2-style API tier (reference: ``DL/nn/keras/*``, 71 files).

Shape-inferring layers + ``Sequential``/``Model`` with
``compile``/``fit``/``evaluate``/``predict``. See ``topology.py``.
"""

from bigdl_tpu.keras.engine import Input, KerasLayer
from bigdl_tpu.keras.layers import (
    Activation,
    AtrousConvolution2D,
    AveragePooling1D,
    AveragePooling2D,
    BatchNormalization,
    Bidirectional,
    ConvLSTM2D,
    Convolution1D,
    Convolution2D,
    Cropping1D,
    Cropping2D,
    Deconvolution2D,
    Dense,
    Dropout,
    ELU,
    Embedding,
    Flatten,
    GRU,
    GaussianDropout,
    GaussianNoise,
    GlobalAveragePooling1D,
    GlobalAveragePooling2D,
    GlobalMaxPooling1D,
    GlobalMaxPooling2D,
    Highway,
    InputLayer,
    LSTM,
    LeakyReLU,
    Masking,
    MaxPooling1D,
    MaxPooling2D,
    MaxoutDense,
    Merge,
    PReLU,
    Permute,
    RepeatVector,
    Reshape,
    SimpleRNN,
    ThresholdedReLU,
    TimeDistributed,
    UpSampling1D,
    UpSampling2D,
    ZeroPadding1D,
    ZeroPadding2D,
    merge,
)
from bigdl_tpu.keras.topology import KerasModel, Model, Sequential

__all__ = [k for k in dir() if not k.startswith("_")]
