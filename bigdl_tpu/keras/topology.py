"""Keras-tier topology: Sequential / Model with compile·fit·evaluate·predict.

Reference: ``DL/nn/keras/Topology.scala`` — ``KerasModel.compile`` (:55),
``fit`` (:89), ``evaluate`` (:127), ``predict`` (:149); ``Model`` (:165,
functional graph), ``Sequential`` (:262).

TPU-native: ``fit`` builds a core :class:`~bigdl_tpu.optim.optimizer.Optimizer`
(jit on one chip, pjit over the mesh when more devices are visible) over an
in-memory ``DataSet``; ``predict``/``evaluate`` run a jitted forward in
batches. Trained params/state live on the model object so the Keras tier is
usable imperatively, like the reference.
"""

from __future__ import annotations

import logging
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.dataset.dataset import AbstractDataSet, DataSet
from bigdl_tpu.dataset.transformer import SampleToMiniBatch
from bigdl_tpu.keras.engine import KerasLayer
from bigdl_tpu.keras.objectives import to_criterion, to_metric, to_optim_method
from bigdl_tpu.nn import containers as C
from bigdl_tpu.nn.graph import Graph, Node
from bigdl_tpu.nn.module import Context, Criterion, Module
from bigdl_tpu.optim.optim_method import OptimMethod
from bigdl_tpu.optim.trigger import Trigger

log = logging.getLogger("bigdl_tpu.keras")


class KerasModel(Module):
    """compile/fit/evaluate/predict mixin (reference ``KerasModel``)."""

    def __init__(self):
        super().__init__()
        self._optim_method: Optional[OptimMethod] = None
        self._criterion: Optional[Criterion] = None
        self._metrics: Optional[list] = None
        self._params = None
        self._state = None
        self._jit_fwd = None
        self._jit_eval = None

    # -- training ----------------------------------------------------------
    def compile(self, optimizer: Union[str, OptimMethod],
                loss: Union[str, Criterion],
                metrics: Optional[Sequence] = None) -> "KerasModel":
        self._optim_method = to_optim_method(optimizer)
        self._criterion = to_criterion(loss)
        self._metrics = [to_metric(m, self._criterion) for m in (metrics or [])]
        self._jit_eval = None  # loss/metrics changed: rebuild the eval step
        return self

    def fit(self, x, y=None, batch_size: int = 32, nb_epoch: int = 10,
            validation_data=None, distributed: Optional[bool] = None):
        """Train. ``x`` may be arrays (with ``y``) or an ``AbstractDataSet``
        yielding MiniBatches."""
        if self._optim_method is None:
            raise RuntimeError("call compile(...) before fit(...)")
        if isinstance(x, AbstractDataSet):
            ds = x
        else:
            ds = DataSet.tensors(np.asarray(x), np.asarray(y)) >> SampleToMiniBatch(batch_size)

        if distributed is None:
            distributed = jax.device_count() > 1
        if distributed:
            from bigdl_tpu.optim.distri_optimizer import DistriOptimizer as Opt
        else:
            from bigdl_tpu.optim.optimizer import LocalOptimizer as Opt
        opt = Opt(self, ds, self._criterion, batch_size=batch_size)
        opt.set_optim_method(self._optim_method)
        opt.set_end_when(Trigger.max_epoch(nb_epoch))
        if self._params is not None:
            opt.set_model_and_state(self._params, self._state)
        if validation_data is not None and self._metrics:
            vx, vy = validation_data
            vds = DataSet.tensors(np.asarray(vx), np.asarray(vy))
            opt.set_validation(Trigger.every_epoch(), vds, self._metrics, batch_size)
        self._params, self._state = opt.optimize()
        return self

    # -- inference ---------------------------------------------------------
    def _require_params(self):
        if self._params is None:
            self._params, self._state = self.init(jax.random.key(0))
        return self._params, self._state or {}

    def _forward_fn(self):
        """Jitted forward, compiled once and cached across calls."""
        if self._jit_fwd is None:
            def fwd(p, s, xb):
                out, _ = self.apply(p, xb, state=s, training=False)
                return out

            self._jit_fwd = jax.jit(fwd)
        return self._jit_fwd

    def _n_inputs(self) -> int:
        return 1

    def predict(self, x, batch_size: int = 32):
        """Forward in batches; returns a stacked np.ndarray
        (reference ``KerasModel.predict``, ``Topology.scala:149``).
        Multi-input functional Models take ``x`` as a list/tuple of
        arrays, batch-sliced together — dispatch is on the MODEL's input
        arity, so a plain Python list of samples for a single-input model
        still reads as one array."""
        params, state = self._require_params()
        fwd = self._forward_fn()
        multi = self._n_inputs() > 1
        xs = [np.asarray(a) for a in x] if multi else [np.asarray(x)]
        if multi:
            if len(xs) != self._n_inputs():
                raise ValueError(
                    f"model has {self._n_inputs()} inputs; got {len(xs)}")
            if any(len(a) != len(xs[0]) for a in xs):
                raise ValueError(
                    "multi-input predict needs equal-length inputs; got "
                    f"{[len(a) for a in xs]} rows")
        outs = []
        for i in range(0, len(xs[0]), batch_size):
            batch = tuple(jnp.asarray(a[i:i + batch_size]) for a in xs)
            outs.append(np.asarray(
                fwd(params, state, batch if multi else batch[0])))
        return np.concatenate(outs, axis=0)

    def predict_classes(self, x, batch_size: int = 32):
        return np.argmax(self.predict(x, batch_size), axis=-1)

    def evaluate(self, x, y, batch_size: int = 32):
        """Returns [(name, value)] for loss + compiled metrics."""
        from bigdl_tpu.optim.validation import Loss, ValidationResult

        from bigdl_tpu.optim.validation import accumulate_batch, split_methods

        params, state = self._require_params()
        methods = [Loss(self._criterion)] + list(self._metrics or [])
        jit_idx, host_idx = split_methods(methods)

        if self._jit_eval is None:
            def eval_fn(p, s, xb, yb):
                out, _ = self.apply(p, xb, state=s, training=False)
                # host-side (non-jit-safe) metrics run on the materialized
                # output outside the jit (see accumulate_batch)
                return out, [methods[i].batch(out, yb) for i in jit_idx]

            self._jit_eval = jax.jit(eval_fn)
        eval_step = self._jit_eval
        x, y = np.asarray(x), np.asarray(y)
        totals = [ValidationResult(0.0, 0, m.name) for m in methods]
        for i in range(0, len(x), batch_size):
            yb = y[i:i + batch_size]
            out, jit_outs = eval_step(params, state, jnp.asarray(x[i:i + batch_size]),
                                      jnp.asarray(yb))
            accumulate_batch(totals, methods, jit_idx, host_idx, jit_outs, out, yb)
        return [(t.name, t.result()[0]) for t in totals]

    # -- weights access ----------------------------------------------------
    def get_weights(self):
        params, _ = self._require_params()
        return params

    def set_weights(self, params, state=None) -> "KerasModel":
        self._params = params
        if state is not None:
            self._state = state
        return self


class Sequential(KerasModel):
    """Linear layer stack with shape inference on ``add``
    (reference ``DL/nn/keras/Topology.scala:262``)."""

    def __init__(self):
        super().__init__()
        self._seq = C.Sequential()
        self._modules.clear()
        self._modules["seq"] = self._seq
        self._layers: list = []

    def add(self, layer: KerasLayer) -> "Sequential":
        if not isinstance(layer, KerasLayer):
            raise TypeError(
                f"keras.Sequential takes Keras-style layers; got {type(layer).__name__} "
                f"(use bigdl_tpu.nn.Sequential for core layers)"
            )
        if self._layers:
            layer.ensure_built(self._layers[-1].get_output_shape())
        else:
            layer.ensure_built()  # needs input_shape=...
        self._layers.append(layer)
        name = layer.get_name() or f"{type(layer).__name__.lower()}_{len(self._layers)}"
        self._seq.add(layer, name)
        return self

    def get_output_shape(self):
        return self._layers[-1].get_output_shape()

    def forward(self, ctx: Context, x):
        return self.run_child(ctx, "seq", x)


class Model(KerasModel):
    """Functional graph model (reference ``Topology.scala:165``)::

        inp = Input(shape=(784,))
        h = Dense(128, activation="relu")(inp)
        out = Dense(10, activation="softmax")(h)
        model = Model(inp, out).compile("sgd", "categorical_crossentropy")
    """

    def __init__(self, input: Union[Node, Sequence[Node]],
                 output: Union[Node, Sequence[Node]]):
        super().__init__()
        self._graph = Graph(input, output)
        self._modules.clear()
        self._modules["graph"] = self._graph
        outs = [output] if isinstance(output, Node) else list(output)
        self._output_shapes = [getattr(n, "keras_shape", None) for n in outs]

    def _n_inputs(self) -> int:
        return len(self._graph.inputs)

    def get_output_shape(self):
        return self._output_shapes[0] if len(self._output_shapes) == 1 else tuple(self._output_shapes)

    def forward(self, ctx: Context, x):
        return self.run_child(ctx, "graph", x)
