"""Keras-1.2-style layer set.

Reference: ``DL/nn/keras/*`` (71 files — Dense, Convolution1D/2D,
MaxPooling, LSTM/GRU/SimpleRNN, Bidirectional, Merge, Embedding,
BatchNormalization, advanced activations, …). Each class here is a
shape-inferring builder over the core layer zoo (see ``engine.py``);
the heavy lifting (conv lowering to ``lax.conv_general_dilated``,
scan-based recurrence, …) lives in ``bigdl_tpu.nn.layers``.

Shapes exclude the batch dim. Image layout is NCHW (Keras "th"
dim-ordering, the reference's default for its Keras tier).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from bigdl_tpu.keras.engine import (
    KerasLayer, Shape, conv_output_length, same_pad_amounts, same_padding,
)
from bigdl_tpu.nn import containers as C
from bigdl_tpu.nn import layers as L
from bigdl_tpu.nn.module import LambdaLayer, Module

# ---------------------------------------------------------------- helpers

_ACTIVATIONS = {
    "relu": L.ReLU,
    "relu6": L.ReLU6,
    "tanh": L.Tanh,
    "sigmoid": L.Sigmoid,
    "hard_sigmoid": L.HardSigmoid,
    "softmax": L.SoftMax,
    "log_softmax": L.LogSoftMax,
    "softplus": L.SoftPlus,
    "softsign": L.SoftSign,
    "elu": L.ELU,
    "gelu": L.GELU,
    "silu": L.SiLU,
    "swish": L.SiLU,
    "linear": L.Identity,
    "identity": L.Identity,
}


def get_activation(name: Optional[str]) -> Optional[Module]:
    if name is None or isinstance(name, Module):
        return name
    try:
        return _ACTIVATIONS[name]()
    except KeyError:
        raise ValueError(
            f"unknown activation {name!r}; known: {sorted(_ACTIVATIONS)}"
        ) from None


def _seq(*modules: Optional[Module]) -> Module:
    mods = [m for m in modules if m is not None]
    if len(mods) == 1:
        return mods[0]
    s = C.Sequential()
    for m in mods:
        s.add(m)
    return s


# ------------------------------------------------------------- core layers


class InputLayer(KerasLayer):
    def build(self, input_shape):
        return L.Identity()

    def compute_output_shape(self, input_shape):
        return input_shape


class Dense(KerasLayer):
    """Fully connected (reference ``DL/nn/keras/Dense.scala``)."""

    def __init__(self, output_dim: int, activation: Optional[str] = None,
                 bias: bool = True, **kw):
        super().__init__(**kw)
        self.output_dim = output_dim
        self.activation = activation
        self.bias = bias

    def build(self, input_shape):
        return _seq(
            L.Linear(input_shape[-1], self.output_dim, with_bias=self.bias),
            get_activation(self.activation),
        )

    def compute_output_shape(self, input_shape):
        return input_shape[:-1] + (self.output_dim,)


class Activation(KerasLayer):
    def __init__(self, activation: str, **kw):
        super().__init__(**kw)
        self.activation = activation

    def build(self, input_shape):
        return get_activation(self.activation)

    def compute_output_shape(self, input_shape):
        return input_shape


class Dropout(KerasLayer):
    def __init__(self, p: float, **kw):
        super().__init__(**kw)
        self.p = p

    def build(self, input_shape):
        return L.Dropout(self.p)

    def compute_output_shape(self, input_shape):
        return input_shape


class Flatten(KerasLayer):
    def build(self, input_shape):
        n = int(math.prod(input_shape))
        return L.Reshape((n,), batch_mode=True)

    def compute_output_shape(self, input_shape):
        return (int(math.prod(input_shape)),)


class Reshape(KerasLayer):
    def __init__(self, target_shape: Sequence[int], **kw):
        super().__init__(**kw)
        self.target_shape = tuple(target_shape)

    def build(self, input_shape):
        tgt = self.compute_output_shape(input_shape)
        return L.Reshape(tgt, batch_mode=True)

    def compute_output_shape(self, input_shape):
        n = int(math.prod(input_shape))
        tgt = list(self.target_shape)
        if -1 in tgt:
            i = tgt.index(-1)
            known = int(math.prod(d for d in tgt if d != -1))
            tgt[i] = n // known
        return tuple(tgt)


class Permute(KerasLayer):
    """Permute non-batch dims; ``dims`` is 1-indexed like Keras."""

    def __init__(self, dims: Sequence[int], **kw):
        super().__init__(**kw)
        self.dims = tuple(dims)

    def build(self, input_shape):
        perm = (0,) + tuple(d for d in self.dims)  # batch + 1-indexed dims
        return LambdaLayer(lambda x: jnp.transpose(x, perm))

    def compute_output_shape(self, input_shape):
        return tuple(input_shape[d - 1] for d in self.dims)


class RepeatVector(KerasLayer):
    def __init__(self, n: int, **kw):
        super().__init__(**kw)
        self.n = n

    def build(self, input_shape):
        n = self.n
        return LambdaLayer(lambda x: jnp.repeat(x[:, None, :], n, axis=1))

    def compute_output_shape(self, input_shape):
        return (self.n,) + tuple(input_shape)


class Masking(KerasLayer):
    """Zero out timesteps equal to ``mask_value`` (soft version: masks the
    features; downstream recurrent layers see zeros)."""

    def __init__(self, mask_value: float = 0.0, **kw):
        super().__init__(**kw)
        self.mask_value = mask_value

    def build(self, input_shape):
        mv = self.mask_value
        def f(x):
            keep = jnp.any(x != mv, axis=-1, keepdims=True)
            return jnp.where(keep, x, 0.0)
        return LambdaLayer(f)

    def compute_output_shape(self, input_shape):
        return input_shape


class Merge(KerasLayer):
    """Merge a list of inputs (reference ``DL/nn/keras/Merge.scala``).
    Modes: sum, mul, max, min, ave, concat, dot, cosine."""

    def __init__(self, mode: str = "sum", concat_axis: int = -1, **kw):
        super().__init__(**kw)
        self.mode = mode
        self.concat_axis = concat_axis

    def build(self, input_shape):
        mode, axis = self.mode, self.concat_axis
        table = {
            "sum": L.CAddTable, "mul": L.CMulTable, "max": L.CMaxTable,
            "min": L.CMinTable, "ave": L.CAveTable,
        }
        if mode in table:
            return table[mode]()
        if mode == "concat":
            return L.JoinTable(axis)
        if mode in ("dot", "cosine"):
            inner = L.DotProduct() if mode == "dot" else L.CosineDistance()

            class _Scalar(Module):
                def __init__(self):
                    super().__init__()
                    self.inner = inner

                def forward(self, ctx, x):
                    # keep a trailing feature dim so the inferred (1,)
                    # shape matches reality for downstream layers
                    return self.run_child(ctx, "inner", x)[..., None]

            return _Scalar()
        raise ValueError(f"unknown merge mode {mode!r}")

    def compute_output_shape(self, input_shape):
        shapes = input_shape  # tuple of shapes
        if self.mode in ("sum", "mul", "max", "min", "ave"):
            return shapes[0]
        if self.mode == "concat":
            axis = self.concat_axis
            idx = axis - 1 if axis > 0 else len(shapes[0]) + axis
            out = list(shapes[0])
            out[idx] = sum(s[idx] for s in shapes)
            return tuple(out)
        return (1,)


def merge(inputs, mode="sum", concat_axis=-1, name=None):
    return Merge(mode=mode, concat_axis=concat_axis, name=name)(inputs)


class GaussianNoise(KerasLayer):
    def __init__(self, sigma: float, **kw):
        super().__init__(**kw)
        self.sigma = sigma

    def build(self, input_shape):
        return L.GaussianNoise(self.sigma)

    def compute_output_shape(self, input_shape):
        return input_shape


class GaussianDropout(KerasLayer):
    def __init__(self, p: float, **kw):
        super().__init__(**kw)
        self.p = p

    def build(self, input_shape):
        return L.GaussianDropout(self.p)

    def compute_output_shape(self, input_shape):
        return input_shape


class Highway(KerasLayer):
    """y = t * h(Wx+b) + (1-t) * x (reference ``DL/nn/keras/Highway``)."""

    def __init__(self, activation: str = "tanh", bias: bool = True, **kw):
        super().__init__(**kw)
        self.activation = activation
        self.bias = bias

    def build(self, input_shape):
        d = input_shape[-1]
        h = L.Linear(d, d, with_bias=self.bias)
        t = L.Linear(d, d, with_bias=self.bias)
        act = get_activation(self.activation)

        class _Highway(Module):
            def __init__(self):
                super().__init__()
                self.h = h
                self.t = t
                self.act = act

            def forward(self, ctx, x):
                hx = self.act.forward(ctx.child("act"), self.run_child(ctx, "h", x))
                tx = jax.nn.sigmoid(self.run_child(ctx, "t", x))
                return tx * hx + (1 - tx) * x

        return _Highway()

    def compute_output_shape(self, input_shape):
        return input_shape


class MaxoutDense(KerasLayer):
    """Max over ``nb_feature`` linear maps (reference ``MaxoutDense``)."""

    def __init__(self, output_dim: int, nb_feature: int = 4, **kw):
        super().__init__(**kw)
        self.output_dim = output_dim
        self.nb_feature = nb_feature

    def build(self, input_shape):
        lin = L.Linear(input_shape[-1], self.output_dim * self.nb_feature)
        k, d = self.nb_feature, self.output_dim

        class _Maxout(Module):
            def __init__(self):
                super().__init__()
                self.lin = lin

            def forward(self, ctx, x):
                z = self.run_child(ctx, "lin", x)
                return jnp.max(z.reshape(z.shape[:-1] + (k, d)), axis=-2)

        return _Maxout()

    def compute_output_shape(self, input_shape):
        return input_shape[:-1] + (self.output_dim,)


# ------------------------------------------------------------ convolution


class Convolution2D(KerasLayer):
    """2-D conv, NCHW (reference ``DL/nn/keras/Convolution2D.scala``)."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 activation: Optional[str] = None, border_mode: str = "valid",
                 subsample: Tuple[int, int] = (1, 1), bias: bool = True, **kw):
        super().__init__(**kw)
        self.nb_filter, self.nb_row, self.nb_col = nb_filter, nb_row, nb_col
        self.activation = activation
        self.border_mode = border_mode
        self.subsample = tuple(subsample)

        self.bias = bias

    def build(self, input_shape):
        cin = input_shape[0]
        pad_layer, ph, pw = None, 0, 0
        if self.border_mode == "same":
            (ph_lo, ph_hi) = same_pad_amounts(self.nb_row)
            (pw_lo, pw_hi) = same_pad_amounts(self.nb_col)
            if ph_lo == ph_hi and pw_lo == pw_hi:
                ph, pw = ph_lo, pw_lo
            else:
                # even kernel: exact 'same' needs asymmetric zero pad
                pad_layer = LambdaLayer(lambda x: jnp.pad(
                    x, ((0, 0), (0, 0), (ph_lo, ph_hi), (pw_lo, pw_hi))))
        return _seq(
            pad_layer,
            L.SpatialConvolution(
                cin, self.nb_filter, self.nb_col, self.nb_row,
                self.subsample[1], self.subsample[0], pw, ph,
                with_bias=self.bias,
            ),
            get_activation(self.activation),
        )

    def compute_output_shape(self, input_shape):
        _, h, w = input_shape
        oh = conv_output_length(h, self.nb_row, self.border_mode, self.subsample[0])
        ow = conv_output_length(w, self.nb_col, self.border_mode, self.subsample[1])
        return (self.nb_filter, oh, ow)


class AtrousConvolution2D(KerasLayer):
    """Dilated 2-D conv (reference ``AtrousConvolution2D``)."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 atrous_rate: Tuple[int, int] = (1, 1),
                 activation: Optional[str] = None,
                 subsample: Tuple[int, int] = (1, 1), bias: bool = True, **kw):
        super().__init__(**kw)
        self.nb_filter, self.nb_row, self.nb_col = nb_filter, nb_row, nb_col
        self.atrous_rate = tuple(atrous_rate)
        self.activation = activation
        self.subsample = tuple(subsample)
        self.bias = bias

    def build(self, input_shape):
        cin = input_shape[0]
        return _seq(
            L.SpatialDilatedConvolution(
                cin, self.nb_filter, self.nb_col, self.nb_row,
                self.subsample[1], self.subsample[0], 0, 0,
                self.atrous_rate[1], self.atrous_rate[0],
            ),
            get_activation(self.activation),
        )

    def compute_output_shape(self, input_shape):
        _, h, w = input_shape
        oh = conv_output_length(h, self.nb_row, "valid", self.subsample[0], self.atrous_rate[0])
        ow = conv_output_length(w, self.nb_col, "valid", self.subsample[1], self.atrous_rate[1])
        return (self.nb_filter, oh, ow)


class Deconvolution2D(KerasLayer):
    """Transposed conv (reference ``Deconvolution2D``)."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 activation: Optional[str] = None,
                 subsample: Tuple[int, int] = (1, 1), bias: bool = True, **kw):
        super().__init__(**kw)
        self.nb_filter, self.nb_row, self.nb_col = nb_filter, nb_row, nb_col
        self.activation = activation
        self.subsample = tuple(subsample)
        self.bias = bias

    def build(self, input_shape):
        cin = input_shape[0]
        return _seq(
            L.SpatialFullConvolution(
                cin, self.nb_filter, self.nb_col, self.nb_row,
                self.subsample[1], self.subsample[0],
                with_bias=self.bias,
            ),
            get_activation(self.activation),
        )

    def compute_output_shape(self, input_shape):
        _, h, w = input_shape
        oh = (h - 1) * self.subsample[0] + self.nb_row
        ow = (w - 1) * self.subsample[1] + self.nb_col
        return (self.nb_filter, oh, ow)


class AtrousConvolution1D(KerasLayer):
    """Dilated 1-D conv (reference ``AtrousConvolution1D``: maps onto a
    width-1 dilated 2-D conv over (steps, 1, dim))."""

    def __init__(self, nb_filter: int, filter_length: int,
                 atrous_rate: int = 1, activation: Optional[str] = None,
                 subsample_length: int = 1, bias: bool = True, **kw):
        super().__init__(**kw)
        self.nb_filter = nb_filter
        self.filter_length = filter_length
        self.atrous_rate = atrous_rate
        self.activation = activation
        self.subsample_length = subsample_length
        self.bias = bias

    def build(self, input_shape):
        steps, dim = input_shape
        conv = L.SpatialDilatedConvolution(
            dim, self.nb_filter, 1, self.filter_length,
            1, self.subsample_length, 0, 0, 1, self.atrous_rate,
            with_bias=self.bias,
        )
        # (B, steps, dim) -> NCHW (B, dim, steps, 1) -> conv -> back
        to4 = LambdaLayer(lambda x: jnp.transpose(x, (0, 2, 1))[:, :, :, None])
        to3 = LambdaLayer(lambda x: jnp.transpose(x[:, :, :, 0], (0, 2, 1)))
        return _seq(to4, conv, to3, get_activation(self.activation))

    def compute_output_shape(self, input_shape):
        steps, _ = input_shape
        eff = self.filter_length + (self.filter_length - 1) * (self.atrous_rate - 1)
        out = conv_output_length(steps, eff, "valid", self.subsample_length)
        return (out, self.nb_filter)


class SoftMax(KerasLayer):
    """Standalone softmax activation layer (reference keras ``SoftMax``)."""

    def build(self, input_shape):
        return get_activation("softmax")

    def compute_output_shape(self, input_shape):
        return input_shape


class Convolution1D(KerasLayer):
    """1-D conv over (steps, dim) inputs (reference ``Convolution1D``)."""

    def __init__(self, nb_filter: int, filter_length: int,
                 activation: Optional[str] = None, border_mode: str = "valid",
                 subsample_length: int = 1, bias: bool = True, **kw):
        super().__init__(**kw)
        self.nb_filter = nb_filter
        self.filter_length = filter_length
        self.activation = activation
        self.border_mode = border_mode
        self.subsample_length = subsample_length
        self.bias = bias

    def build(self, input_shape):
        steps, dim = input_shape
        conv = L.TemporalConvolution(
            dim, self.nb_filter, self.filter_length, self.subsample_length,
        )
        if self.border_mode == "same":
            lo, hi = same_pad_amounts(self.filter_length)
            pad = LambdaLayer(lambda x: jnp.pad(x, ((0, 0), (lo, hi), (0, 0))))
            return _seq(pad, conv, get_activation(self.activation))
        return _seq(conv, get_activation(self.activation))

    def compute_output_shape(self, input_shape):
        steps, _ = input_shape
        out = conv_output_length(steps, self.filter_length, self.border_mode,
                                 self.subsample_length)
        return (out, self.nb_filter)


class ZeroPadding1D(KerasLayer):
    def __init__(self, padding: int = 1, **kw):
        super().__init__(**kw)
        self.padding = padding

    def build(self, input_shape):
        p = self.padding
        return LambdaLayer(lambda x: jnp.pad(x, ((0, 0), (p, p), (0, 0))))

    def compute_output_shape(self, input_shape):
        return (input_shape[0] + 2 * self.padding,) + tuple(input_shape[1:])


class ZeroPadding2D(KerasLayer):
    def __init__(self, padding: Tuple[int, int] = (1, 1), **kw):
        super().__init__(**kw)
        self.padding = tuple(padding)

    def build(self, input_shape):
        ph, pw = self.padding
        return LambdaLayer(
            lambda x: jnp.pad(x, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
        )

    def compute_output_shape(self, input_shape):
        c, h, w = input_shape
        return (c, h + 2 * self.padding[0], w + 2 * self.padding[1])


class Cropping1D(KerasLayer):
    def __init__(self, cropping: Tuple[int, int] = (1, 1), **kw):
        super().__init__(**kw)
        self.cropping = tuple(cropping)

    def build(self, input_shape):
        a, b = self.cropping
        end = input_shape[0] - b
        return LambdaLayer(lambda x: x[:, a:end])

    def compute_output_shape(self, input_shape):
        return (input_shape[0] - sum(self.cropping),) + tuple(input_shape[1:])


class Cropping2D(KerasLayer):
    def __init__(self, cropping=((0, 0), (0, 0)), **kw):
        super().__init__(**kw)
        self.cropping = tuple(map(tuple, cropping))

    def build(self, input_shape):
        (t, b), (l, r) = self.cropping
        _, h, w = input_shape
        return LambdaLayer(lambda x: x[:, :, t:h - b, l:w - r])

    def compute_output_shape(self, input_shape):
        c, h, w = input_shape
        (t, b), (l, r) = self.cropping
        return (c, h - t - b, w - l - r)


class UpSampling1D(KerasLayer):
    def __init__(self, length: int = 2, **kw):
        super().__init__(**kw)
        self.length = length

    def build(self, input_shape):
        n = self.length
        return LambdaLayer(lambda x: jnp.repeat(x, n, axis=1))

    def compute_output_shape(self, input_shape):
        return (input_shape[0] * self.length,) + tuple(input_shape[1:])


class UpSampling2D(KerasLayer):
    def __init__(self, size: Tuple[int, int] = (2, 2), **kw):
        super().__init__(**kw)
        self.size = tuple(size)

    def build(self, input_shape):
        sh, sw = self.size
        return LambdaLayer(
            lambda x: jnp.repeat(jnp.repeat(x, sh, axis=2), sw, axis=3)
        )

    def compute_output_shape(self, input_shape):
        c, h, w = input_shape
        return (c, h * self.size[0], w * self.size[1])


# ---------------------------------------------------------------- pooling


class _Pool2D(KerasLayer):
    """'same' uses symmetric padding of (pool-1)//2 — exact Keras 'same'
    for odd pool sizes; for even pool sizes this degrades to 'valid'
    behavior, and the inferred shape below reports that truthfully."""

    pool_cls = None

    def __init__(self, pool_size: Tuple[int, int] = (2, 2),
                 strides: Optional[Tuple[int, int]] = None,
                 border_mode: str = "valid", **kw):
        super().__init__(**kw)
        self.pool_size = tuple(pool_size)
        self.strides = tuple(strides) if strides else self.pool_size
        self.border_mode = border_mode

    def _pads(self):
        if self.border_mode == "same":
            return same_padding(self.pool_size[0]), same_padding(self.pool_size[1])
        return 0, 0

    def build(self, input_shape):
        ph, pw = self._pads()
        return self.pool_cls(
            self.pool_size[1], self.pool_size[0],
            self.strides[1], self.strides[0], pw, ph,
        )

    def compute_output_shape(self, input_shape):
        c, h, w = input_shape
        ph, pw = self._pads()
        oh = (h + 2 * ph - self.pool_size[0]) // self.strides[0] + 1
        ow = (w + 2 * pw - self.pool_size[1]) // self.strides[1] + 1
        return (c, oh, ow)


class MaxPooling2D(_Pool2D):
    pool_cls = L.SpatialMaxPooling


class AveragePooling2D(_Pool2D):
    pool_cls = L.SpatialAveragePooling


class MaxPooling1D(KerasLayer):
    def __init__(self, pool_length: int = 2, stride: Optional[int] = None, **kw):
        super().__init__(**kw)
        self.pool_length = pool_length
        self.stride = stride or pool_length

    def build(self, input_shape):
        return L.TemporalMaxPooling(self.pool_length, self.stride)

    def compute_output_shape(self, input_shape):
        out = conv_output_length(input_shape[0], self.pool_length, "valid", self.stride)
        return (out, input_shape[1])


class AveragePooling1D(KerasLayer):
    def __init__(self, pool_length: int = 2, stride: Optional[int] = None, **kw):
        super().__init__(**kw)
        self.pool_length = pool_length
        self.stride = stride or pool_length

    def build(self, input_shape):
        k, s = self.pool_length, self.stride
        def f(x):
            n = (x.shape[1] - k) // s + 1
            idx = jnp.arange(n) * s
            # strided window gather: (B, n, k, D) -> mean over k
            gather = x[:, idx[:, None] + jnp.arange(k)[None, :], :]
            return jnp.mean(gather, axis=2)
        return LambdaLayer(f)

    def compute_output_shape(self, input_shape):
        out = conv_output_length(input_shape[0], self.pool_length, "valid", self.stride)
        return (out, input_shape[1])


class GlobalMaxPooling1D(KerasLayer):
    def build(self, input_shape):
        return LambdaLayer(lambda x: jnp.max(x, axis=1))

    def compute_output_shape(self, input_shape):
        return (input_shape[-1],)


class GlobalAveragePooling1D(KerasLayer):
    def build(self, input_shape):
        return LambdaLayer(lambda x: jnp.mean(x, axis=1))

    def compute_output_shape(self, input_shape):
        return (input_shape[-1],)


class GlobalMaxPooling2D(KerasLayer):
    def build(self, input_shape):
        return L.GlobalMaxPooling2D()

    def compute_output_shape(self, input_shape):
        return (input_shape[0],)


class GlobalAveragePooling2D(KerasLayer):
    def build(self, input_shape):
        return L.GlobalAveragePooling2D()

    def compute_output_shape(self, input_shape):
        return (input_shape[0],)


# -------------------------------------------------------------- recurrent


class _KerasRecurrent(KerasLayer):
    def __init__(self, output_dim: int, activation: str = "tanh",
                 return_sequences: bool = False, go_backwards: bool = False, **kw):
        super().__init__(**kw)
        self.output_dim = output_dim
        self.activation = activation
        self.return_sequences = return_sequences
        self.go_backwards = go_backwards

    def make_cell(self, input_dim: int):
        raise NotImplementedError

    def build(self, input_shape):
        cell = self.make_cell(input_shape[-1])
        return L.Recurrent(cell, return_sequences=self.return_sequences,
                           reverse=self.go_backwards)

    def compute_output_shape(self, input_shape):
        if self.return_sequences:
            return (input_shape[0], self.output_dim)
        return (self.output_dim,)


class SimpleRNN(_KerasRecurrent):
    def make_cell(self, input_dim):
        return L.RnnCell(input_dim, self.output_dim, activation=self.activation)


class LSTM(_KerasRecurrent):
    def make_cell(self, input_dim):
        return L.LSTMCell(input_dim, self.output_dim, activation=self.activation)


class GRU(_KerasRecurrent):
    def make_cell(self, input_dim):
        return L.GRUCell(input_dim, self.output_dim, activation=self.activation)


class ConvLSTM2D(KerasLayer):
    """Convolutional LSTM over (steps, channels, h, w) inputs."""

    def __init__(self, nb_filter: int, nb_kernel: int = 3,
                 return_sequences: bool = False, **kw):
        super().__init__(**kw)
        self.nb_filter = nb_filter
        self.nb_kernel = nb_kernel
        self.return_sequences = return_sequences

    def build(self, input_shape):
        _, cin, h, w = input_shape
        cell = L.ConvLSTMPeepholeCell(cin, self.nb_filter, self.nb_kernel)
        return L.Recurrent(cell, return_sequences=self.return_sequences)

    def compute_output_shape(self, input_shape):
        t, _, h, w = input_shape
        out = (self.nb_filter, h, w)
        return (t,) + out if self.return_sequences else out


class Bidirectional(KerasLayer):
    """Wrap a recurrent Keras layer front-and-back (reference
    ``DL/nn/keras/Bidirectional.scala``)."""

    def __init__(self, layer: _KerasRecurrent, merge_mode: str = "concat", **kw):
        super().__init__(**kw)
        self.layer = layer
        if merge_mode not in ("concat", "sum"):
            raise ValueError(
                f"unsupported Bidirectional merge_mode {merge_mode!r} "
                f"(supported: 'concat', 'sum')"
            )
        self.merge_mode = merge_mode

    def build(self, input_shape):
        fwd = self.layer.make_cell(input_shape[-1])
        bwd = self.layer.make_cell(input_shape[-1])
        if not self.layer.return_sequences:
            raise ValueError("Bidirectional requires return_sequences=True")
        return L.BiRecurrent(fwd, bwd, merge=self.merge_mode)

    def compute_output_shape(self, input_shape):
        d = self.layer.output_dim
        if self.merge_mode == "concat":
            d *= 2
        return (input_shape[0], d)


class TimeDistributed(KerasLayer):
    """Apply an inner Keras layer to every timestep."""

    def __init__(self, layer: KerasLayer, **kw):
        super().__init__(**kw)
        self.layer = layer

    def build(self, input_shape):
        self.layer.ensure_built(tuple(input_shape[1:]))
        return L.TimeDistributed(self.layer)

    def compute_output_shape(self, input_shape):
        return (input_shape[0],) + tuple(self.layer.get_output_shape())


# ------------------------------------------------- embedding / norm / act


class Embedding(KerasLayer):
    def __init__(self, input_dim: int, output_dim: int, **kw):
        super().__init__(**kw)
        self.input_dim = input_dim
        self.output_dim = output_dim

    def build(self, input_shape):
        return L.LookupTable(self.input_dim, self.output_dim)

    def compute_output_shape(self, input_shape):
        return tuple(input_shape) + (self.output_dim,)


class BatchNormalization(KerasLayer):
    def __init__(self, epsilon: float = 1e-3, momentum: float = 0.99, **kw):
        super().__init__(**kw)
        self.epsilon = epsilon
        self.momentum = momentum

    def build(self, input_shape):
        if len(input_shape) == 3:  # NCHW feature maps
            return L.SpatialBatchNormalization(
                input_shape[0], eps=self.epsilon, momentum=1 - self.momentum,
            )
        return L.BatchNormalization(
            input_shape[-1], eps=self.epsilon, momentum=1 - self.momentum,
        )

    def compute_output_shape(self, input_shape):
        return input_shape


class LeakyReLU(KerasLayer):
    def __init__(self, alpha: float = 0.3, **kw):
        super().__init__(**kw)
        self.alpha = alpha

    def build(self, input_shape):
        return L.LeakyReLU(self.alpha)

    def compute_output_shape(self, input_shape):
        return input_shape


class ELU(KerasLayer):
    def __init__(self, alpha: float = 1.0, **kw):
        super().__init__(**kw)
        self.alpha = alpha

    def build(self, input_shape):
        return L.ELU(self.alpha)

    def compute_output_shape(self, input_shape):
        return input_shape


class PReLU(KerasLayer):
    def build(self, input_shape):
        return L.PReLU()

    def compute_output_shape(self, input_shape):
        return input_shape


class ThresholdedReLU(KerasLayer):
    def __init__(self, theta: float = 1.0, **kw):
        super().__init__(**kw)
        self.theta = theta

    def build(self, input_shape):
        return L.Threshold(self.theta, 0.0)

    def compute_output_shape(self, input_shape):
        return input_shape


# -------------------------------------------------------- 3-D / extra tier


class Convolution3D(KerasLayer):
    """3-D conv over (channels, dim1, dim2, dim3) (reference
    ``DL/nn/keras/Convolution3D.scala``)."""

    def __init__(self, nb_filter: int, kernel_dim1: int, kernel_dim2: int,
                 kernel_dim3: int, activation: Optional[str] = None,
                 subsample: Tuple[int, int, int] = (1, 1, 1),
                 bias: bool = True, **kw):
        super().__init__(**kw)
        self.nb_filter = nb_filter
        self.kernel = (kernel_dim1, kernel_dim2, kernel_dim3)
        self.activation = activation
        self.subsample = tuple(subsample)
        self.bias = bias

    def build(self, input_shape):
        cin = input_shape[0]
        return _seq(
            L.VolumetricConvolution(
                cin, self.nb_filter,
                self.kernel[0], self.kernel[2], self.kernel[1],
                self.subsample[0], self.subsample[2], self.subsample[1],
                with_bias=self.bias,
            ),
            get_activation(self.activation),
        )

    def compute_output_shape(self, input_shape):
        _, d1, d2, d3 = input_shape
        dims = tuple(
            conv_output_length(n, k, "valid", s)
            for n, k, s in zip((d1, d2, d3), self.kernel, self.subsample)
        )
        return (self.nb_filter,) + dims


class _Pool3D(KerasLayer):
    mode = "max"

    def __init__(self, pool_size: Tuple[int, int, int] = (2, 2, 2),
                 strides: Optional[Tuple[int, int, int]] = None, **kw):
        super().__init__(**kw)
        self.pool_size = tuple(pool_size)
        self.strides = tuple(strides) if strides else self.pool_size

    def build(self, input_shape):
        cls = L.VolumetricMaxPooling if self.mode == "max" else L.VolumetricAveragePooling
        k, s = self.pool_size, self.strides
        return cls(k[0], k[2], k[1], s[0], s[2], s[1])

    def compute_output_shape(self, input_shape):
        c = input_shape[0]
        dims = tuple(
            conv_output_length(n, k, "valid", s)
            for n, k, s in zip(input_shape[1:], self.pool_size, self.strides)
        )
        return (c,) + dims


class MaxPooling3D(_Pool3D):
    mode = "max"


class AveragePooling3D(_Pool3D):
    mode = "avg"


class GlobalMaxPooling3D(KerasLayer):
    def build(self, input_shape):
        return LambdaLayer(lambda x: jnp.max(x, axis=(2, 3, 4)))

    def compute_output_shape(self, input_shape):
        return (input_shape[0],)


class GlobalAveragePooling3D(KerasLayer):
    def build(self, input_shape):
        return LambdaLayer(lambda x: jnp.mean(x, axis=(2, 3, 4)))

    def compute_output_shape(self, input_shape):
        return (input_shape[0],)


class ZeroPadding3D(KerasLayer):
    def __init__(self, padding: Tuple[int, int, int] = (1, 1, 1), **kw):
        super().__init__(**kw)
        self.padding = tuple(padding)

    def build(self, input_shape):
        p1, p2, p3 = self.padding
        return LambdaLayer(lambda x: jnp.pad(
            x, ((0, 0), (0, 0), (p1, p1), (p2, p2), (p3, p3))))

    def compute_output_shape(self, input_shape):
        c, d1, d2, d3 = input_shape
        p1, p2, p3 = self.padding
        return (c, d1 + 2 * p1, d2 + 2 * p2, d3 + 2 * p3)


class Cropping3D(KerasLayer):
    def __init__(self, cropping=((1, 1), (1, 1), (1, 1)), **kw):
        super().__init__(**kw)
        self.cropping = tuple(map(tuple, cropping))

    def build(self, input_shape):
        return L.Cropping3D(*self.cropping)

    def compute_output_shape(self, input_shape):
        c = input_shape[0]
        return (c,) + tuple(
            n - a - b for n, (a, b) in zip(input_shape[1:], self.cropping)
        )


class UpSampling3D(KerasLayer):
    def __init__(self, size: Tuple[int, int, int] = (2, 2, 2), **kw):
        super().__init__(**kw)
        self.size = tuple(size)

    def build(self, input_shape):
        return L.UpSampling3D(self.size)

    def compute_output_shape(self, input_shape):
        c = input_shape[0]
        return (c,) + tuple(n * s for n, s in zip(input_shape[1:], self.size))


class _KerasSpatialDropout(KerasLayer):
    cls = None

    def __init__(self, p: float = 0.5, **kw):
        super().__init__(**kw)
        self.p = p

    def build(self, input_shape):
        return self.cls(self.p)

    def compute_output_shape(self, input_shape):
        return input_shape


class SpatialDropout1D(_KerasSpatialDropout):
    cls = L.SpatialDropout1D


class SpatialDropout2D(_KerasSpatialDropout):
    cls = L.SpatialDropout2D


class SpatialDropout3D(_KerasSpatialDropout):
    cls = L.SpatialDropout3D


class SeparableConvolution2D(KerasLayer):
    """Depthwise + pointwise conv (reference ``SeparableConvolution2D``)."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 depth_multiplier: int = 1, activation: Optional[str] = None,
                 subsample: Tuple[int, int] = (1, 1), bias: bool = True, **kw):
        super().__init__(**kw)
        self.nb_filter, self.nb_row, self.nb_col = nb_filter, nb_row, nb_col
        self.depth_multiplier = depth_multiplier
        self.activation = activation
        self.subsample = tuple(subsample)
        self.bias = bias

    def build(self, input_shape):
        return _seq(
            L.SpatialSeparableConvolution(
                input_shape[0], self.nb_filter, self.depth_multiplier,
                self.nb_col, self.nb_row, self.subsample[1], self.subsample[0],
                with_bias=self.bias,
            ),
            get_activation(self.activation),
        )

    def compute_output_shape(self, input_shape):
        _, h, w = input_shape
        oh = conv_output_length(h, self.nb_row, "valid", self.subsample[0])
        ow = conv_output_length(w, self.nb_col, "valid", self.subsample[1])
        return (self.nb_filter, oh, ow)


class LocallyConnected2D(KerasLayer):
    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 activation: Optional[str] = None,
                 subsample: Tuple[int, int] = (1, 1), bias: bool = True, **kw):
        super().__init__(**kw)
        self.nb_filter, self.nb_row, self.nb_col = nb_filter, nb_row, nb_col
        self.activation = activation
        self.subsample = tuple(subsample)
        self.bias = bias

    def build(self, input_shape):
        c, h, w = input_shape
        return _seq(
            L.LocallyConnected2D(
                c, w, h, self.nb_filter, self.nb_col, self.nb_row,
                self.subsample[1], self.subsample[0], with_bias=self.bias,
            ),
            get_activation(self.activation),
        )

    def compute_output_shape(self, input_shape):
        _, h, w = input_shape
        oh = conv_output_length(h, self.nb_row, "valid", self.subsample[0])
        ow = conv_output_length(w, self.nb_col, "valid", self.subsample[1])
        return (self.nb_filter, oh, ow)


class LocallyConnected1D(KerasLayer):
    def __init__(self, nb_filter: int, filter_length: int,
                 activation: Optional[str] = None,
                 subsample_length: int = 1, **kw):
        super().__init__(**kw)
        self.nb_filter = nb_filter
        self.filter_length = filter_length
        self.activation = activation
        self.subsample_length = subsample_length

    def build(self, input_shape):
        steps, dim = input_shape
        return _seq(
            L.LocallyConnected1D(steps, dim, self.nb_filter,
                                 self.filter_length, self.subsample_length),
            get_activation(self.activation),
        )

    def compute_output_shape(self, input_shape):
        out = conv_output_length(input_shape[0], self.filter_length, "valid",
                                 self.subsample_length)
        return (out, self.nb_filter)


class SReLU(KerasLayer):
    def build(self, input_shape):
        return L.SReLU(input_shape)

    def compute_output_shape(self, input_shape):
        return input_shape
