"""Keras-style name → framework-object mappings.

Reference: the Scala Keras tier accepts strings in ``compile`` for
optimizer / loss / metrics (``DL/nn/keras/Topology.scala:55-87``,
``KerasUtils.toBigDLCriterion`` / ``toBigDLOptimMethod``).

Label convention: classification losses here take INTEGER class labels
(the framework-native convention, like the reference's ClassNLLCriterion
1-based targets made 0-based); ``categorical_crossentropy`` accepts
either int labels or one-hot rows (argmax'd on the fly).
"""

from __future__ import annotations

from typing import Union

import jax
import jax.numpy as jnp

from bigdl_tpu.nn import criterion as Cr
from bigdl_tpu.nn.module import Criterion
from bigdl_tpu.optim import optim_method as Om
from bigdl_tpu.optim.optim_method import OptimMethod
from bigdl_tpu.optim.validation import Loss, Top1Accuracy, Top5Accuracy, ValidationMethod


class _CategoricalCrossEntropy(Criterion):
    """Cross-entropy over softmax probabilities (Keras semantics); accepts
    one-hot or integer targets."""

    def __init__(self, from_logits: bool = False):
        self.from_logits = from_logits

    def forward(self, output, target):
        if self.from_logits:
            logp = output - jax.nn.logsumexp(output, axis=-1, keepdims=True)
        else:
            logp = jnp.log(jnp.clip(output, 1e-8, 1.0))
        if target.ndim == output.ndim:  # one-hot
            target = jnp.argmax(target, axis=-1)
        onehot = jnp.take_along_axis(logp, target[..., None].astype(jnp.int32), axis=-1)
        return -jnp.mean(onehot)


_LOSSES = {
    "mse": Cr.MSECriterion,
    "mean_squared_error": Cr.MSECriterion,
    "mae": Cr.AbsCriterion,
    "mean_absolute_error": Cr.AbsCriterion,
    "categorical_crossentropy": _CategoricalCrossEntropy,
    "sparse_categorical_crossentropy": _CategoricalCrossEntropy,
    "binary_crossentropy": Cr.BCECriterion,
    "hinge": Cr.MarginCriterion,
    "kld": Cr.DistKLDivCriterion,
    "kullback_leibler_divergence": Cr.DistKLDivCriterion,
    "nll": Cr.ClassNLLCriterion,
    "crossentropy_from_logits": Cr.CrossEntropyCriterion,
}

_OPTIMIZERS = {
    "sgd": lambda: Om.SGD(learning_rate=0.01),
    "adam": lambda: Om.Adam(),
    "adamax": lambda: Om.Adamax(),
    "adagrad": lambda: Om.Adagrad(),
    "adadelta": lambda: Om.Adadelta(),
    "rmsprop": lambda: Om.RMSprop(),
}


def to_criterion(loss: Union[str, Criterion]) -> Criterion:
    if isinstance(loss, Criterion):
        return loss
    try:
        return _LOSSES[loss.lower()]()
    except KeyError:
        raise ValueError(f"unknown loss {loss!r}; known: {sorted(_LOSSES)}") from None


def to_optim_method(opt: Union[str, OptimMethod]) -> OptimMethod:
    if isinstance(opt, OptimMethod):
        return opt
    try:
        return _OPTIMIZERS[opt.lower()]()
    except KeyError:
        raise ValueError(f"unknown optimizer {opt!r}; known: {sorted(_OPTIMIZERS)}") from None


def to_metric(metric, criterion: Criterion) -> ValidationMethod:
    if isinstance(metric, ValidationMethod):
        return metric
    name = str(metric).lower()
    if name in ("accuracy", "acc", "top1", "top1accuracy"):
        return Top1Accuracy()
    if name in ("top5", "top5accuracy"):
        return Top5Accuracy()
    if name == "loss":
        return Loss(criterion)
    raise ValueError(f"unknown metric {metric!r}")
