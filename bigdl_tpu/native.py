"""ctypes bindings for the native runtime library (``native/``).

Reference analogue: the JNI surface of BigDL-core (SURVEY.md §2.1) —
here scoped to the runtime around XLA compute: CRC32C/record framing,
aligned host buffers, a threaded prefetch ring, and hot uint8 image loops.

The library auto-builds with ``make`` on first use (g++ is in the image);
every entry point has a pure-python/numpy fallback so the package works
even without a toolchain. ``native_available()`` reports which path is
active.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                           "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libbigdl_native.so")

_lib = None
_lib_lock = threading.Lock()
_build_failed = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    with _lib_lock:
        if _lib is not None or _build_failed:
            return _lib
        src = os.path.join(_NATIVE_DIR, "bigdl_native.cpp")
        stale = (not os.path.exists(_LIB_PATH)
                 or (os.path.exists(src)
                     and os.path.getmtime(src) > os.path.getmtime(_LIB_PATH)))
        if stale:
            try:
                # make's own dependency rule rebuilds when the source is
                # newer — a prebuilt stale .so would miss newer symbols
                subprocess.run(["make", "-s"], cwd=_NATIVE_DIR, check=True,
                               capture_output=True, timeout=120)
            except Exception:
                if not os.path.exists(_LIB_PATH):
                    _build_failed = True
                    return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            _build_failed = True
            return None
        lib.bigdl_crc32c.restype = ctypes.c_uint32
        lib.bigdl_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint32]
        lib.bigdl_masked_crc32c.restype = ctypes.c_uint32
        lib.bigdl_masked_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.bigdl_ring_new.restype = ctypes.c_void_p
        lib.bigdl_ring_new.argtypes = [ctypes.c_uint64]
        lib.bigdl_ring_free.argtypes = [ctypes.c_void_p]
        lib.bigdl_ring_close.argtypes = [ctypes.c_void_p]
        lib.bigdl_ring_push.restype = ctypes.c_int
        lib.bigdl_ring_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
        lib.bigdl_ring_pop.restype = ctypes.c_int64
        lib.bigdl_ring_pop.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64]
        lib.bigdl_ring_peek_size.restype = ctypes.c_int64
        lib.bigdl_ring_peek_size.argtypes = [ctypes.c_void_p]
        lib.bigdl_ring_size.restype = ctypes.c_int64
        lib.bigdl_ring_size.argtypes = [ctypes.c_void_p]
        lib.bigdl_normalize_u8.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_float,
            ctypes.c_int,
        ]
        lib.bigdl_hflip_u8.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int,
        ]
        lib.bigdl_crop_u8.argtypes = [ctypes.c_void_p, ctypes.c_void_p] + \
            [ctypes.c_int64] * 7
        lib.bigdl_batch_hwc_to_nchw_f32.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_float, ctypes.c_int,
        ]
        if hasattr(lib, "bigdl_tfrecord_scan"):  # absent in a stale .so
            lib.bigdl_tfrecord_scan.restype = ctypes.c_int64
            lib.bigdl_tfrecord_scan.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_int, ctypes.POINTER(ctypes.c_int64),
            ]
        _lib = lib
    return _lib


def native_available() -> bool:
    return _load() is not None


# ------------------------------------------------------------------ crc


def crc32c(data: bytes, seed: int = 0) -> int:
    lib = _load()
    if lib is not None:
        return lib.bigdl_crc32c(data, len(data), seed & 0xFFFFFFFF)
    from bigdl_tpu.visualization.events import crc32c as py_crc

    if seed:
        raise NotImplementedError("python fallback supports seed=0 only")
    return py_crc(data)


def masked_crc32c(data: bytes) -> int:
    lib = _load()
    if lib is not None:
        return lib.bigdl_masked_crc32c(data, len(data))
    from bigdl_tpu.visualization.events import masked_crc32c as py_masked

    return py_masked(data)


# ---------------------------------------------------------- prefetch ring


class PrefetchRing:
    """Bounded byte-buffer queue backed by the native MPMC ring (python
    ``queue.Queue`` fallback). The host-side staging stage between storage
    reader threads and the device-infeed loop (reference analogue:
    ``ThreadPool``-driven transformer pipelines)."""

    def __init__(self, capacity: int = 8):
        self._lib = _load()
        self._closed = False
        if self._lib is not None:
            self._h = self._lib.bigdl_ring_new(capacity)
            self._q = None
        else:
            import queue

            self._h = None
            self._q = queue.Queue(maxsize=capacity)

    def push(self, data: bytes) -> bool:
        if self._h is not None:
            return self._lib.bigdl_ring_push(self._h, data, len(data)) == 0
        import queue

        # poll so a producer blocked on a full ring observes close(), like
        # the native ring where close() wakes blocked pushers
        while not self._closed:
            try:
                self._q.put(data, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def pop(self) -> Optional[bytes]:
        """Next payload, or None once the ring is closed AND drained.
        Zero-length payloads are legal records, not end-of-stream."""
        if self._h is not None:
            n = self._lib.bigdl_ring_peek_size(self._h)
            if n < 0:  # closed-and-drained (-1); 0 is a legal empty record
                return None
            buf = ctypes.create_string_buffer(max(int(n), 1))
            got = self._lib.bigdl_ring_pop(self._h, buf, n)
            if got < 0:
                return None
            return buf.raw[:got]
        import queue

        while True:
            try:
                item = self._q.get(timeout=0.05)
            except queue.Empty:
                if self._closed:
                    return None  # closed and drained
                continue
            return item

    def close(self) -> None:
        if self._h is not None:
            self._lib.bigdl_ring_close(self._h)
        self._closed = True

    def __len__(self) -> int:
        if self._h is not None:
            return int(self._lib.bigdl_ring_size(self._h))
        return self._q.qsize()

    def __del__(self):
        if getattr(self, "_h", None) is not None and self._lib is not None:
            try:
                self._lib.bigdl_ring_free(self._h)
            except Exception:
                pass
            self._h = None


# ------------------------------------------------------------- image ops


def normalize_u8(images: np.ndarray, mean, std, scale: float = 1.0,
                 n_threads: int = 4) -> np.ndarray:
    """(N, C, H, W) uint8 -> float32 ``(x/scale - mean[c]) / std[c]``."""
    images = np.ascontiguousarray(images, dtype=np.uint8)
    n, c, h, w = images.shape
    mean = np.ascontiguousarray(np.broadcast_to(np.asarray(mean, np.float32), (c,)))
    std = np.ascontiguousarray(np.broadcast_to(np.asarray(std, np.float32), (c,)))
    lib = _load()
    if lib is not None:
        out = np.empty((n, c, h, w), np.float32)
        lib.bigdl_normalize_u8(
            images.ctypes.data, out.ctypes.data, n, c, h * w,
            mean.ctypes.data, std.ctypes.data, ctypes.c_float(scale), n_threads,
        )
        return out
    return ((images.astype(np.float32) / scale) - mean[None, :, None, None]) \
        / std[None, :, None, None]


def hflip_u8(images: np.ndarray, n_threads: int = 4) -> np.ndarray:
    """Horizontal flip of (N, C, H, W) uint8; always returns a NEW array
    and leaves the input untouched (both native and numpy paths)."""
    lib = _load()
    if lib is not None:
        # np.array copies exactly once (ascontiguousarray + .copy() would
        # copy twice for non-contiguous / non-uint8 inputs)
        images = np.array(images, dtype=np.uint8, order="C")
        n, c, h, w = images.shape
        lib.bigdl_hflip_u8(images.ctypes.data, n, c, h, w, n_threads)
        return images
    images = np.asarray(images, dtype=np.uint8)
    return images[..., ::-1].copy()


def crop_u8(image: np.ndarray, y0: int, x0: int, ch: int, cw: int) -> np.ndarray:
    """(C, H, W) uint8 crop."""
    image = np.ascontiguousarray(image, dtype=np.uint8)
    c, h, w = image.shape
    if y0 < 0 or x0 < 0 or y0 + ch > h or x0 + cw > w:
        raise ValueError("crop window out of bounds")
    lib = _load()
    if lib is not None:
        out = np.empty((c, ch, cw), np.uint8)
        lib.bigdl_crop_u8(image.ctypes.data, out.ctypes.data, c, h, w, y0, x0, ch, cw)
        return out
    return image[:, y0:y0 + ch, x0:x0 + cw].copy()


def batch_hwc_to_nchw(images: np.ndarray, mean, std, scale: float = 1.0,
                      n_threads: int = 4) -> np.ndarray:
    """(N, H, W, C) uint8 decoded images -> (N, C, H, W) float32
    normalized batch in ONE pass (transpose + normalize fused; the
    reference's ``MTLabeledBGRImgToBatch`` hot loop). Numpy fallback when
    the native library is unavailable."""
    images = np.ascontiguousarray(images, dtype=np.uint8)
    n, h, w, c = images.shape
    mean = np.ascontiguousarray(np.broadcast_to(np.asarray(mean, np.float32), (c,)))
    std = np.ascontiguousarray(np.broadcast_to(np.asarray(std, np.float32), (c,)))
    lib = _load()
    if lib is None:
        x = images.astype(np.float32) / scale
        x = (x - mean) / std
        return np.ascontiguousarray(x.transpose(0, 3, 1, 2))
    out = np.empty((n, c, h, w), np.float32)
    lib.bigdl_batch_hwc_to_nchw_f32(
        images.ctypes.data_as(ctypes.c_void_p), out.ctypes.data_as(ctypes.c_void_p),
        n, h, w, c, mean.ctypes.data_as(ctypes.c_void_p),
        std.ctypes.data_as(ctypes.c_void_p), ctypes.c_float(scale), n_threads)
    return out


def tfrecord_scan(buf, start: int = 0, cap: int = 65536,
                  verify: bool = True):
    """Native one-pass TFRecord framing scan over an in-memory/mmapped
    file: returns ``(offsets, lengths, truncated)`` — int64 payload
    positions with both CRCs validated in C, plus whether the buffer ends
    mid-record (records before the truncation ARE returned, matching the
    tolerant streaming reader's in-progress-shard behavior). Returns
    None when the native library is unavailable. Raises IOError on a
    corrupt CRC. ``buf`` is anything buffer-like (bytes, mmap).

    ``cap`` bounds one call; resume from
    ``offsets[-1] + lengths[-1] + 4``."""
    lib = _load()
    if lib is None or not hasattr(lib, "bigdl_tfrecord_scan"):
        return None
    arr = np.frombuffer(buf, np.uint8)  # zero-copy view; works on mmap
    offsets = np.empty(cap, np.int64)
    lengths = np.empty(cap, np.int64)
    err = ctypes.c_int64(-1)
    n = lib.bigdl_tfrecord_scan(
        arr.ctypes.data_as(ctypes.c_void_p), arr.size, start,
        offsets.ctypes.data_as(ctypes.c_void_p),
        lengths.ctypes.data_as(ctypes.c_void_p), cap, int(verify),
        ctypes.byref(err))
    # release the buffer export BEFORE raising: the exception traceback
    # pins this frame, and a pinned export would make an mmap'd caller's
    # close() fail with BufferError
    del arr
    if n == -1:
        raise IOError(f"corrupt tfrecord crc at byte {err.value}")
    return offsets[:n], lengths[:n], err.value >= 0
