"""Finding reporters: human-readable text and machine-readable JSON."""

from __future__ import annotations

import json
from collections import Counter
from typing import Dict, List, Sequence

from .registry import Finding, all_rules


def format_text(new: Sequence[Finding], baselined: Sequence[Finding],
                stale: Sequence[dict], suppressed_count: int = 0) -> str:
    lines: List[str] = []
    for f in new:
        lines.append(f"{f.location()}: {f.rule_id} {f.message}")
    if new:
        lines.append("")
    by_rule = Counter(f.rule_id for f in new)
    summary = ", ".join(f"{rid}={n}" for rid, n in sorted(by_rule.items()))
    lines.append(
        f"graftlint: {len(new)} new finding(s)"
        + (f" [{summary}]" if summary else "")
        + f", {len(baselined)} baselined, {suppressed_count} suppressed"
        + (f", {len(stale)} STALE baseline entr"
           f"{'y' if len(stale) == 1 else 'ies'} (fixed sites — "
           "re-run with --write-baseline to shrink the baseline)"
           if stale else ""))
    if stale:
        for e in stale:
            lines.append(
                f"  stale: {e.get('path')}:{e.get('line')} "
                f"{e.get('rule')} [{e.get('fingerprint')}]")
    return "\n".join(lines)


def to_json(new: Sequence[Finding], baselined: Sequence[Finding],
            stale: Sequence[dict], suppressed_count: int = 0) -> Dict:
    return {
        "new": [f.to_json() for f in new],
        "baselined": [f.to_json() for f in baselined],
        "stale_baseline_entries": list(stale),
        "suppressed": suppressed_count,
        "summary": {
            "new": len(new),
            "baselined": len(baselined),
            "stale": len(stale),
        },
    }


def format_rules_table() -> str:
    lines = ["graftlint rules:", ""]
    for rule in all_rules():
        lines.append(f"  {rule.rule_id}  {rule.title}")
    return "\n".join(lines)


def dump_json(path: str, payload: Dict) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=1)
        fh.write("\n")
