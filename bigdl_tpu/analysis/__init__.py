"""graftlint — repo-native static analysis.

Sixteen PRs of review hardening kept re-finding the same bug classes by
hand: shared exception instances raised across threads, ``time.sleep``
inside a critical section, busy-wait poll loops where a condition
variable exists, raw (non-keyed) RNG breaking schedule invariance,
leaked threads, silently-swallowed exceptions, and compile-heavy tests
leaking into the tier-1 budget.  This package turns those review
findings into machine-checked rules that run on every commit:

    python -m bigdl_tpu.analysis --baseline .graftlint-baseline.json

Each rule has a stable ID (``GL001``..), emits ``path:line`` findings,
honours inline ``# graftlint: disable=GL00X`` suppressions, and matches
against a checked-in baseline file so pre-existing, triaged-as-
acceptable debt is frozen while any NEW violation fails the run.  The
runtime half (lock-order cycle detection + leaked-thread assertions)
lives in ``tests/_sanitizers.py`` as an always-on pytest plugin.
"""

from .registry import Finding, Rule, all_rules, get_rule
from .walker import SourceFile, walk_tree
from .baseline import load_baseline, write_baseline, split_by_baseline
from .runner import run_analysis

__all__ = [
    "Finding",
    "Rule",
    "SourceFile",
    "all_rules",
    "get_rule",
    "walk_tree",
    "load_baseline",
    "write_baseline",
    "split_by_baseline",
    "run_analysis",
]
