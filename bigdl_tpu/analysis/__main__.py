"""CLI: ``python -m bigdl_tpu.analysis [paths...] [options]``.

Exit status 0 when every finding is baselined or suppressed; 1 when any
new finding exists (or a scanned file fails to parse).  The CI job runs

    python -m bigdl_tpu.analysis --baseline .graftlint-baseline.json \
        --json graftlint-findings.json

and uploads the findings JSON as an artifact.
"""

from __future__ import annotations

import argparse
import os
import sys

from .baseline import load_baseline, split_by_baseline, write_baseline
from .report import dump_json, format_rules_table, format_text, to_json
from .runner import run_analysis


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m bigdl_tpu.analysis",
        description="graftlint — repo-native static analysis",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="files/dirs to scan, relative to --root "
             "(default: bigdl_tpu tests perf bench.py)")
    parser.add_argument("--root", default=None,
                        help="repo root (default: auto-detected from the "
                             "installed package location, else cwd)")
    parser.add_argument("--baseline", default=None,
                        help="baseline JSON; findings present in it pass "
                             "(default: .graftlint-baseline.json under "
                             "--root when that file exists)")
    parser.add_argument("--write-baseline", action="store_true",
                        help="rewrite --baseline from the current findings "
                             "(preserving notes on surviving entries)")
    parser.add_argument("--json", default=None, metavar="PATH",
                        help="dump machine-readable findings JSON")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule ids to run (default all)")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)

    if args.list_rules:
        print(format_rules_table())
        return 0

    root = args.root
    if root is None:
        # the package lives at <root>/bigdl_tpu/analysis/
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        root = pkg_root if os.path.isdir(
            os.path.join(pkg_root, "bigdl_tpu")) else os.getcwd()

    rule_ids = (args.rules.split(",") if args.rules else None)
    findings, suppressed = run_analysis(root, args.paths or None, rule_ids)

    baseline_path = args.baseline
    if baseline_path is None:
        default = os.path.join(root, ".graftlint-baseline.json")
        if os.path.exists(default):
            baseline_path = default
    if baseline_path and not os.path.isabs(baseline_path):
        # relative baselines are always root-relative — resolving against
        # the cwd instead would make `--write-baseline` clobber whatever
        # same-named file happens to live where the tool was launched
        baseline_path = os.path.join(root, baseline_path)
    baseline = load_baseline(baseline_path) if baseline_path else {}

    if args.write_baseline:
        if not baseline_path:
            parser.error("--write-baseline requires --baseline")
        notes = {fp: e["note"] for fp, e in baseline.items() if e.get("note")}
        write_baseline(baseline_path, findings, notes)
        print(f"graftlint: wrote {len(findings)} entr"
              f"{'y' if len(findings) == 1 else 'ies'} to {baseline_path}")
        return 0

    new, baselined, stale = split_by_baseline(findings, baseline)
    print(format_text(new, baselined, stale, suppressed))
    if args.json:
        dump_json(args.json, to_json(new, baselined, stale, suppressed))
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
