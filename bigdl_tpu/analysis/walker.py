"""Source-tree walker: file discovery, parsing, suppression comments.

One :class:`SourceFile` per ``.py`` file carries the raw text, split
lines, the parsed AST (with parent back-links, which several rules need
to find the enclosing function/class), and the per-line suppression
table parsed from ``# graftlint: disable=GL001[,GL002|all]`` comments.
A suppression comment on the flagged line OR on the immediately
preceding (otherwise-blank) line silences the finding.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterator, List, Optional, Sequence, Set

# generated protobuf modules and caches are never lint targets
_SKIP_DIRS = {"__pycache__", ".git", ".github", "node_modules"}
_SKIP_SUFFIXES = ("_pb2.py",)

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable=([A-Za-z0-9_,\s]+)")


class SourceFile:
    def __init__(self, path: str, text: str,
                 tree: Optional[ast.AST], parse_error: Optional[str]):
        self.path = path          # repo-relative, forward slashes
        self.text = text
        self.lines = text.splitlines()
        self.tree = tree
        self.parse_error = parse_error
        self.suppressions = _parse_suppressions(self.lines)

    def suppressed(self, line: int, rule_id: str) -> bool:
        rules = self.suppressions.get(line)
        return rules is not None and (rule_id in rules or "all" in rules)


def _parse_suppressions(lines: Sequence[str]) -> Dict[int, Set[str]]:
    table: Dict[int, Set[str]] = {}
    for i, raw in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(raw)
        if not m:
            continue
        rules = {tok.strip() for tok in m.group(1).split(",") if tok.strip()}
        table.setdefault(i, set()).update(rules)
        # a standalone suppression comment covers the next line too
        if raw.split("#", 1)[0].strip() == "":
            table.setdefault(i + 1, set()).update(rules)
    return table


def _add_parents(tree: ast.AST) -> None:
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child._graftlint_parent = node  # type: ignore[attr-defined]


def parent(node: ast.AST) -> Optional[ast.AST]:
    return getattr(node, "_graftlint_parent", None)


def enclosing(node: ast.AST, *types) -> Optional[ast.AST]:
    """Nearest ancestor of one of the given AST types (or None)."""
    cur = parent(node)
    while cur is not None:
        if isinstance(cur, types):
            return cur
        cur = parent(cur)
    return None


def load_source(root: str, relpath: str) -> SourceFile:
    full = os.path.join(root, relpath)
    with open(full, "r", encoding="utf-8", errors="replace") as fh:
        text = fh.read()
    tree: Optional[ast.AST] = None
    err: Optional[str] = None
    try:
        tree = ast.parse(text, filename=relpath)
        _add_parents(tree)
    except SyntaxError as e:  # surfaced as a finding by the runner
        err = f"syntax error: {e.msg} (line {e.lineno})"
    return SourceFile(relpath.replace(os.sep, "/"), text, tree, err)


def discover(root: str, paths: Optional[Sequence[str]] = None) -> List[str]:
    """Repo-relative ``.py`` paths under the given roots (sorted)."""
    if not paths:
        paths = ["bigdl_tpu", "tests", "perf", "bench.py"]
    found: List[str] = []
    for p in paths:
        full = os.path.join(root, p)
        if os.path.isfile(full) and p.endswith(".py"):
            found.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(full):
            dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
            for fn in sorted(filenames):
                if not fn.endswith(".py") or fn.endswith(_SKIP_SUFFIXES):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, fn), root)
                found.append(rel.replace(os.sep, "/"))
    return sorted(set(found))


def walk_tree(root: str,
              paths: Optional[Sequence[str]] = None) -> Iterator[SourceFile]:
    for rel in discover(root, paths):
        yield load_source(root, rel)
