"""Run the rule set over a source tree and collect findings."""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from . import rules as _rules  # noqa: F401  (registers the rule set)
from .registry import Finding, all_rules, finalize_findings
from .walker import walk_tree


def run_analysis(
        root: str,
        paths: Optional[Sequence[str]] = None,
        rule_ids: Optional[Sequence[str]] = None,
) -> Tuple[List[Finding], int]:
    """All non-suppressed findings over ``root`` + the suppressed count.

    Findings come back fingerprinted and sorted by (path, line, rule).
    A file that fails to parse yields a single GL000 finding — a syntax
    error must fail the lint run, not silently skip the file.
    """
    active = [r for r in all_rules()
              if rule_ids is None or r.rule_id in rule_ids]
    raw: List[Finding] = []
    suppressed = 0
    for src in walk_tree(root, paths):
        if src.parse_error is not None:
            raw.append(Finding("GL000", src.path, 1, src.parse_error))
            continue
        for rule in active:
            if not rule.applies_to(src.path):
                continue
            for f in rule.check(src):
                if src.suppressed(f.line, f.rule_id):
                    suppressed += 1
                else:
                    raw.append(f)
    return finalize_findings(raw), suppressed
