"""Baseline I/O — freeze triaged debt, fail everything new.

The baseline is a checked-in JSON file mapping finding fingerprints to
their triage note.  A finding whose fingerprint appears in the baseline
is reported as *baselined* (informational) and does not fail the run;
anything else does.  Stale entries (fingerprints no longer produced by
the tree) are reported so the baseline only shrinks — re-run with
``--write-baseline`` after fixing sites to drop them.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List, Tuple

from .registry import Finding

BASELINE_VERSION = 1


def load_baseline(path: str) -> Dict[str, dict]:
    """fingerprint -> entry dict.  Missing file = empty baseline."""
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    if not isinstance(data, dict) or "entries" not in data:
        raise ValueError(f"{path}: not a graftlint baseline file")
    out: Dict[str, dict] = {}
    for entry in data["entries"]:
        out[entry["fingerprint"]] = entry
    return out


def write_baseline(path: str, findings: Iterable[Finding],
                   notes: Dict[str, str] = None) -> None:
    notes = notes or {}
    entries = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule_id)):
        entry = f.to_json()
        note = notes.get(f.fingerprint)
        if note:
            entry["note"] = note
        entries.append(entry)
    payload = {"version": BASELINE_VERSION, "entries": entries}
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=1, sort_keys=False)
        fh.write("\n")
    os.replace(tmp, path)


def split_by_baseline(
        findings: Iterable[Finding], baseline: Dict[str, dict]
) -> Tuple[List[Finding], List[Finding], List[dict]]:
    """(new, baselined, stale-baseline-entries)."""
    new: List[Finding] = []
    old: List[Finding] = []
    seen = set()
    for f in findings:
        if f.fingerprint in baseline:
            old.append(f)
            seen.add(f.fingerprint)
        else:
            new.append(f)
    stale = [e for fp, e in baseline.items() if fp not in seen]
    return new, old, stale
