"""The graftlint rule set — one class per review-hardening bug class.

Every rule here is a generalization of a bug a human reviewer actually
caught in this repo (PR numbers in each docstring).  Keep rules cheap
and syntactic: a false positive costs one inline suppression comment
with a justification; a false negative costs a review round-trip.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set

from .registry import Finding, Rule, register
from .walker import SourceFile, enclosing, parent

# ---------------------------------------------------------------- helpers

_EXC_NAME_SUFFIXES = ("Error", "Exception", "Fault", "Warning", "Interrupt",
                      "Exit", "Cancelled", "Overloaded", "Unavailable")


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None


def _looks_like_exception_class(name: str) -> bool:
    base = name.rsplit(".", 1)[-1]
    return base[:1].isupper() and (
        base.endswith(_EXC_NAME_SUFFIXES) or base in {
            "Exception", "BaseException", "StopIteration", "KeyboardInterrupt",
        })


def _const_number(node: ast.AST) -> Optional[float]:
    if isinstance(node, ast.Constant) and isinstance(node.value, (int, float)):
        return float(node.value)
    return None


def _is_sleep_call(node: ast.Call) -> bool:
    name = dotted(node.func)
    return name in ("time.sleep", "sleep")


def _walk_stop_at_defs(node: ast.AST) -> Iterator[ast.AST]:
    """Walk a statement body without descending into nested def/class
    bodies (their execution is deferred — a sleep there does not run
    under the enclosing ``with lock``)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        cur = stack.pop()
        yield cur
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(cur))


def _lockish(expr: ast.AST) -> Optional[str]:
    """Name of a lock-like ``with`` context (lock/mutex/cv/cond), or None.

    A ``Condition`` counts: a sleep while holding the underlying lock
    blocks every waiter exactly like a plain mutex.
    """
    name = dotted(expr)
    if isinstance(expr, ast.Call):
        # with self._lock.acquire_timeout(...), with lock() — look inside
        name = dotted(expr.func)
    if not name:
        return None
    tail = name.rsplit(".", 1)[-1].lower().lstrip("_")
    if any(tok in tail for tok in ("lock", "mutex")) or tail in (
            "cv", "cond", "condition"):
        return name
    return None


def _in_package(path: str, *roots: str) -> bool:
    return any(path == r or path.startswith(r + "/") for r in roots)


# ----------------------------------------------------------------- GL001


@register
class SharedExceptionInstance(Rule):
    """Raise of a shared exception *instance* stored on self/module.

    PR 8: a fault plan armed with an exception INSTANCE raised the same
    object on every firing; a later raise mutated the ``__traceback__``
    of an exception a stream had already captured.  Raising any object
    that outlives the raise site (a module-level singleton, an attribute
    on self/cls) aliases traceback and ``__context__`` state across
    threads.  Fix: store the class + args (or a factory) and raise a
    fresh copy per site, e.g. ``raise copy.copy(self._err)`` or
    ``raise type(e)(*e.args)``.
    """

    rule_id = "GL001"
    title = "raise of shared exception instance"

    def check(self, src: SourceFile) -> Iterator[Finding]:
        # module-level NAME = SomeError(...) singletons
        module_instances: Set[str] = set()
        for stmt in src.tree.body:
            if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
                fn = dotted(stmt.value.func)
                if fn and _looks_like_exception_class(fn):
                    for tgt in stmt.targets:
                        if isinstance(tgt, ast.Name):
                            module_instances.add(tgt.id)

        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Raise) or node.exc is None:
                continue
            exc = node.exc
            if isinstance(exc, ast.Name) and exc.id in module_instances:
                yield self.finding(
                    src, node.lineno,
                    f"raises module-level exception instance `{exc.id}` — "
                    "a shared object whose __traceback__/__context__ is "
                    "mutated by every raise; raise a fresh instance")
            elif isinstance(exc, ast.Attribute):
                base = dotted(exc.value)
                if base in ("self", "cls") and not self._fresh_in_scope(
                        node, exc.attr):
                    yield self.finding(
                        src, node.lineno,
                        f"raises stored exception instance `{base}.{exc.attr}`"
                        " — shared across raise sites/threads; raise a fresh"
                        " copy (copy.copy / re-construct from class+args)")

    @staticmethod
    def _fresh_in_scope(raise_node: ast.Raise, attr: str) -> bool:
        """True if ``self.<attr>`` is assigned from a constructor call in
        the same function before use — a per-call instance, not shared."""
        fn = enclosing(raise_node, ast.FunctionDef, ast.AsyncFunctionDef)
        if fn is None:
            return False
        for sub in ast.walk(fn):
            if (isinstance(sub, ast.Assign) and isinstance(sub.value, ast.Call)
                    and any(isinstance(t, ast.Attribute) and t.attr == attr
                            and dotted(t.value) in ("self", "cls")
                            for t in sub.targets)):
                return True
        return False


# ----------------------------------------------------------------- GL002


@register
class SleepUnderLock(Rule):
    """``time.sleep`` while holding a lock.

    PR 8: a latency fault effect slept inside the injector's registry
    lock and stalled every unrelated site check in the process.  A sleep
    under a lock converts one slow path into a global convoy; move the
    sleep outside the critical section (or use ``Condition.wait`` with a
    timeout, which releases the lock while blocking).
    """

    rule_id = "GL002"
    title = "time.sleep while holding a lock"

    def check(self, src: SourceFile) -> Iterator[Finding]:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.With):
                continue
            lock_name = None
            for item in node.items:
                lock_name = _lockish(item.context_expr)
                if lock_name:
                    break
            if not lock_name:
                continue
            for sub in _walk_stop_at_defs(node):
                if isinstance(sub, ast.Call) and _is_sleep_call(sub):
                    yield self.finding(
                        src, sub.lineno,
                        f"time.sleep inside `with {lock_name}:` — blocks "
                        "every other acquirer for the full sleep; move it "
                        "outside the critical section or use Condition.wait")


# ----------------------------------------------------------------- GL003


@register
class BusyWaitPollLoop(Rule):
    """Busy-wait poll loop: ``while ...: ... time.sleep(short)``.

    PR 4/8 replaced fixed-interval poll loops (host_prefetch put-retry,
    the replica prober) with condition-woken waits — a poll loop burns a
    core, adds up to one full interval of wake-up latency, and hides
    shutdown races.  Flagged when a while-loop body sleeps a constant
    <= 0.5 s; use ``threading.Event.wait`` / ``Condition.wait_for`` with
    a deadline instead.
    """

    rule_id = "GL003"
    title = "busy-wait poll loop (while + short sleep)"
    MAX_POLL_SLEEP = 0.5

    def applies_to(self, path: str) -> bool:
        # tests legitimately poll observable side effects with deadlines;
        # library code has Condition/Event infrastructure to use instead
        return not _in_package(path, "tests")

    def check(self, src: SourceFile) -> Iterator[Finding]:
        for node in ast.walk(src.tree):
            if not isinstance(node, (ast.While, ast.For)):
                continue
            for sub in _walk_stop_at_defs(node):
                if (isinstance(sub, ast.Call) and _is_sleep_call(sub)
                        and sub.args):
                    val = _const_number(sub.args[0])
                    if val is not None and 0 < val <= self.MAX_POLL_SLEEP:
                        yield self.finding(
                            src, sub.lineno,
                            f"poll loop sleeping {val} s per iteration — "
                            "use Event.wait/Condition.wait_for with a "
                            "deadline (condition-woken, no added latency)")


# ----------------------------------------------------------------- GL004


@register
class RawNondeterminism(Rule):
    """Raw (non-keyed) RNG in library code.

    PR 4/6: schedule invariance — a stream being a pure function of its
    seed regardless of worker count, admission order, or chunking — is a
    repo-wide contract, and it dies the moment library code draws from
    process-global or ad-hoc RNG state.  All library randomness routes
    through ``core.rng`` (splitmix64 ``element_seed`` keys, per-request
    threefry, ``RandomGenerator``).  Flags ``np.random.*`` /
    ``random.*`` module state and any argless ``default_rng()``.
    """

    rule_id = "GL004"
    title = "raw nondeterministic RNG outside core/rng.py"

    def applies_to(self, path: str) -> bool:
        return (_in_package(path, "bigdl_tpu")
                and not _in_package(path, "bigdl_tpu/examples")
                and path != "bigdl_tpu/core/rng.py")

    def check(self, src: SourceFile) -> Iterator[Finding]:
        random_names = self._random_module_aliases(src)
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Attribute):
                name = dotted(node)
                if name in ("np.random", "numpy.random"):
                    # fire on the innermost `np.random` node exactly once
                    # per chain; report the full np.random.X chain text
                    yield self.finding(
                        src, node.lineno,
                        f"`{self._chain_text(node)}` — np.random state is "
                        "not keyed; route through core.rng "
                        "(RandomGenerator / element_seed)")
                elif (name and isinstance(node.value, ast.Name)
                      and node.value.id in random_names):
                    yield self.finding(
                        src, node.lineno,
                        f"`{name}` — stdlib random module state is not "
                        "keyed; route through core.rng")
            if (isinstance(node, ast.Call) and not node.args
                    and not node.keywords):
                fname = dotted(node.func)
                if fname and fname.rsplit(".", 1)[-1] == "default_rng":
                    yield self.finding(
                        src, node.lineno,
                        "argless default_rng() — OS-entropy seeded, "
                        "unreproducible; derive the seed via core.rng")

    @staticmethod
    def _random_module_aliases(src: SourceFile) -> Set[str]:
        names: Set[str] = set()
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == "random":
                        names.add(alias.asname or "random")
        return names

    @staticmethod
    def _chain_text(node: ast.Attribute) -> str:
        # report the full chain the attribute participates in, if any
        p = parent(node)
        outer = node
        while isinstance(p, ast.Attribute):
            outer = p
            p = parent(p)
        return dotted(outer) or dotted(node) or "np.random"


# ----------------------------------------------------------------- GL005


@register
class UnmanagedThread(Rule):
    """``threading.Thread(...)`` without ``daemon=`` or a join path.

    PR 5/8: an unclosed engine's loop thread pinned params+cache through
    a strong ref forever; the fix pattern is an explicit lifecycle —
    either ``daemon=True`` (the process may exit under it) or a
    non-daemon thread with a reachable ``join()``.  A Thread created
    with neither is a leak the chaos drain gates only catch dynamically.
    """

    rule_id = "GL005"
    title = "thread without daemon= or join path"

    def applies_to(self, path: str) -> bool:
        return _in_package(path, "bigdl_tpu")

    def check(self, src: SourceFile) -> Iterator[Finding]:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = dotted(node.func)
            if fname not in ("threading.Thread", "Thread"):
                continue
            if any(kw.arg == "daemon" for kw in node.keywords):
                continue
            if self._has_lifecycle(src, node):
                continue
            yield self.finding(
                src, node.lineno,
                "threading.Thread without daemon= and no visible join/"
                ".daemon assignment for its target — leaked on close; "
                "set daemon= explicitly or register a join path")

    @staticmethod
    def _has_lifecycle(src: SourceFile, call: ast.Call) -> bool:
        """Assigned to a name/attr that is joined or daemon-flagged
        somewhere in the same file — directly, or through a list built by
        a comprehension and joined via a for-loop variable."""
        assign = call._graftlint_parent if hasattr(
            call, "_graftlint_parent") else None
        # walk up through comprehension/list nesting to the Assign
        while assign is not None and not isinstance(assign, ast.Assign):
            if isinstance(assign, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.Module)):
                assign = None
                break
            assign = parent(assign)
        target_attr: Optional[str] = None
        if isinstance(assign, ast.Assign) and len(assign.targets) == 1:
            tgt = assign.targets[0]
            if isinstance(tgt, ast.Attribute):
                target_attr = tgt.attr
            elif isinstance(tgt, ast.Name):
                target_attr = tgt.id
        if not target_attr:
            return False
        # `for t in <target>:` loop variables inherit the lifecycle check
        loop_vars: Set[str] = set()
        for node in ast.walk(src.tree):
            if isinstance(node, ast.For) and isinstance(node.target, ast.Name):
                it = node.iter
                it_name = (it.id if isinstance(it, ast.Name)
                           else it.attr if isinstance(it, ast.Attribute)
                           else None)
                if it_name == target_attr:
                    loop_vars.add(node.target.id)
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Attribute) and node.attr == "join":
                base = node.value
                if (isinstance(base, ast.Attribute)
                        and base.attr == target_attr) or (
                        isinstance(base, ast.Name)
                        and base.id in loop_vars | {target_attr}):
                    return True
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if (isinstance(tgt, ast.Attribute)
                            and tgt.attr == "daemon"):
                        base = tgt.value
                        if (isinstance(base, ast.Attribute)
                                and base.attr == target_attr) or (
                                isinstance(base, ast.Name)
                                and base.id == target_attr):
                            return True
        return False


# ----------------------------------------------------------------- GL006


@register
class SilentExceptionSwallow(Rule):
    """Broad ``except Exception:`` that swallows without logging/raising.

    Review keeps finding these late: a swallowed exception turns a hard
    failure into a silent wrong answer (the PR-7 torn-manifest and PR-3
    failed-async-save classes both started as silent passes).  Flagged
    when a bare/``Exception``/``BaseException`` handler body neither
    re-raises nor logs.  Fix by narrowing the exception type, logging at
    the right level, or re-raising; baseline only sites where silence is
    the documented contract (best-effort cleanup).
    """

    rule_id = "GL006"
    title = "broad except that silently swallows"

    def check(self, src: SourceFile) -> Iterator[Finding]:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not self._broad(node.type):
                continue
            if self._handles(node):
                continue
            yield self.finding(
                src, node.lineno,
                "broad except swallows the exception without logging or "
                "re-raising — narrow the type, log it, or re-raise")

    @staticmethod
    def _broad(t: Optional[ast.AST]) -> bool:
        if t is None:
            return True
        names = []
        if isinstance(t, ast.Tuple):
            names = [dotted(e) for e in t.elts]
        else:
            names = [dotted(t)]
        return any(n in ("Exception", "BaseException") for n in names)

    _LOG_NAMES = {"debug", "info", "warning", "warn", "error", "exception",
                  "critical", "log", "print", "print_exc", "format_exc"}

    def _handles(self, handler: ast.ExceptHandler) -> bool:
        """Body re-raises, logs, returns a failure value to the caller,
        or actually *uses* the captured exception object (``as e`` bound
        and referenced — stored, forwarded to a future/callback).  A
        body that merely runs cleanup while dropping the exception value
        still swallows it."""
        for sub in _walk_stop_at_defs(handler):
            if isinstance(sub, ast.Raise):
                return True
            if isinstance(sub, ast.Return) and sub.value is not None:
                return True
            if (handler.name and isinstance(sub, ast.Name)
                    and sub.id == handler.name
                    and isinstance(sub.ctx, ast.Load)):
                return True
            if isinstance(sub, ast.Call):
                fname = dotted(sub.func) or ""
                if fname.rsplit(".", 1)[-1] in self._LOG_NAMES:
                    return True
        return False


# ----------------------------------------------------------------- GL007


@register
class UnmarkedExpensiveTest(Rule):
    """Multi-process / 8-device-mesh test without ``@pytest.mark.slow``.

    ROADMAP: tier-1 runs ``-m 'not slow'`` under a 1200 s wall-clock
    budget (~230 s headroom); every compile-heavy 8-device equivalence
    test and every multi-process test belongs behind the slow marker.
    This rule enforces the budget mechanically: a test (or fixture) that
    spawns processes or builds a >= 8-device mesh must carry the marker
    at function, class, or module level — or a suppression comment
    documenting why it is cheap enough for tier-1.
    """

    rule_id = "GL007"
    title = "expensive test without @pytest.mark.slow"
    MESH_DEVICES_THRESHOLD = 8

    def applies_to(self, path: str) -> bool:
        return _in_package(path, "tests")

    def check(self, src: SourceFile) -> Iterator[Finding]:
        module_slow = self._module_slow(src.tree)
        if module_slow:
            return
        for node in ast.walk(src.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            is_test = node.name.startswith("test")
            is_fixture = any(
                "fixture" in (self._decorator_name(d) or "")
                for d in node.decorator_list)
            if not (is_test or is_fixture):
                continue
            if self._marked_slow(node) or self._class_slow(node):
                continue
            reason = self._expensive(node)
            if reason:
                kind = "fixture" if is_fixture and not is_test else "test"
                yield self.finding(
                    src, node.lineno,
                    f"{kind} `{node.name}` {reason} but has no "
                    "@pytest.mark.slow — tier-1 budget pays for it")

    @staticmethod
    def _decorator_name(d: ast.AST) -> Optional[str]:
        if isinstance(d, ast.Call):
            d = d.func
        return dotted(d)

    @staticmethod
    def _module_slow(tree: ast.AST) -> bool:
        for stmt in tree.body:
            if (isinstance(stmt, ast.Assign)
                    and any(isinstance(t, ast.Name) and t.id == "pytestmark"
                            for t in stmt.targets)):
                text = ast.dump(stmt.value)
                if "'slow'" in text or "slow" in text:
                    return True
        return False

    @staticmethod
    def _marked_slow(fn: ast.AST) -> bool:
        for d in fn.decorator_list:
            name = dotted(d) or dotted(getattr(d, "func", ast.Constant(0)))
            if name and name.endswith("mark.slow"):
                return True
        return False

    @staticmethod
    def _class_slow(fn: ast.AST) -> bool:
        cls = enclosing(fn, ast.ClassDef)
        return cls is not None and UnmarkedExpensiveTest._marked_slow(cls)

    def _expensive(self, fn: ast.AST) -> Optional[str]:
        for sub in ast.walk(fn):
            if isinstance(sub, ast.keyword) and sub.arg == "processes":
                if (isinstance(sub.value, ast.Constant)
                        and sub.value.value is True):
                    return "spawns worker processes (processes=True)"
            if isinstance(sub, ast.Attribute):
                name = dotted(sub)
                if name and name.split(".", 1)[0] in ("multiprocessing",
                                                      "subprocess"):
                    return f"uses {name.split('.', 1)[0]}"
            if isinstance(sub, ast.Call):
                fname = dotted(sub.func) or ""
                base = fname.rsplit(".", 1)[-1]
                if base == "Popen":
                    return "spawns a subprocess (Popen)"
                if base == "serving_meshes" and len(sub.args) >= 1:
                    n = _const_number(sub.args[0])
                    tp = _const_number(sub.args[1]) if len(sub.args) > 1 else 1
                    if (n is not None and tp is not None
                            and n * tp >= self.MESH_DEVICES_THRESHOLD):
                        return (f"builds a {int(n * tp)}-device mesh "
                                "(serving_meshes)")
                if base == "Mesh":
                    for inner in ast.walk(sub):
                        if not isinstance(inner, ast.Call) or not inner.args:
                            continue
                        iname = dotted(inner.func) or ""
                        if iname.endswith("reshape"):
                            k = _const_number(inner.args[0])
                            if k is not None and (
                                    k >= self.MESH_DEVICES_THRESHOLD):
                                return f"builds a {int(k)}-device Mesh"
        return None
