"""Rule registry and the ``Finding`` record.

A rule is a small class with a stable ``rule_id``, a path scope, and a
``check(src)`` generator over one parsed :class:`~.walker.SourceFile`.
Registration happens at class-definition time via ``@register`` so the
CLI, the baseline machinery, and the test fixtures all see the same
list without a hand-maintained table.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Dict, Iterable, Iterator, List, Type


@dataclass(frozen=True)
class Finding:
    """One rule violation at one source location.

    ``fingerprint`` is the baseline identity: a hash of the rule id, the
    repo-relative path, the *normalized text* of the flagged line, and an
    occurrence index among identical lines in the same file — so a
    baselined finding survives unrelated edits shifting its line number,
    but editing the flagged line itself (or adding a new identical
    violation) surfaces as new.
    """

    rule_id: str
    path: str
    line: int
    message: str
    snippet: str = ""
    fingerprint: str = ""

    def location(self) -> str:
        return f"{self.path}:{self.line}"

    def to_json(self) -> dict:
        return {
            "rule": self.rule_id,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "snippet": self.snippet,
            "fingerprint": self.fingerprint,
        }


def compute_fingerprint(rule_id: str, path: str, norm_snippet: str,
                        occurrence: int) -> str:
    payload = f"{rule_id}|{path}|{norm_snippet}|{occurrence}"
    return hashlib.sha1(payload.encode("utf-8")).hexdigest()[:16]


def finalize_findings(findings: Iterable[Finding]) -> List[Finding]:
    """Assign occurrence-indexed fingerprints (stable within one run)."""
    out: List[Finding] = []
    seen: Dict[tuple, int] = {}
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule_id)):
        norm = " ".join(f.snippet.split())
        key = (f.rule_id, f.path, norm)
        occ = seen.get(key, 0)
        seen[key] = occ + 1
        out.append(Finding(f.rule_id, f.path, f.line, f.message,
                           f.snippet, compute_fingerprint(
                               f.rule_id, f.path, norm, occ)))
    return out


class Rule:
    """Base class for graftlint rules."""

    rule_id: str = ""
    title: str = ""
    rationale: str = ""

    def applies_to(self, path: str) -> bool:
        """Path scope (repo-relative, forward slashes). Default: all."""
        return True

    def check(self, src) -> Iterator[Finding]:  # pragma: no cover
        raise NotImplementedError

    def finding(self, src, line: int, message: str) -> Finding:
        snippet = ""
        if 1 <= line <= len(src.lines):
            snippet = src.lines[line - 1].strip()
        return Finding(self.rule_id, src.path, line, message, snippet)


_REGISTRY: Dict[str, Rule] = {}


def register(cls: Type[Rule]) -> Type[Rule]:
    if not cls.rule_id:
        raise ValueError(f"rule {cls.__name__} has no rule_id")
    if cls.rule_id in _REGISTRY:
        raise ValueError(f"duplicate rule id {cls.rule_id}")
    _REGISTRY[cls.rule_id] = cls()
    return cls


def all_rules() -> List[Rule]:
    return [_REGISTRY[k] for k in sorted(_REGISTRY)]


def get_rule(rule_id: str) -> Rule:
    return _REGISTRY[rule_id]
