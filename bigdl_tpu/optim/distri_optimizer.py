"""Distributed (SPMD) optimizer.

Reference: ``DL/optim/DistriOptimizer.scala`` (§3.1 of SURVEY.md) — the
synchronous data-parallel trainer over a BlockManager parameter server
(``AllReduceParameter``): per-iteration weight all-gather, gradient
reduce-scatter with fp16 wire compression, per-partition optimizer update
(ZeRO-1-like state partitioning), straggler dropping, two Spark jobs per
step.

TPU-native: the entire protocol is replaced by sharding one jitted train
step over a ``jax.sharding.Mesh``:

- batch sharded over the ``dp`` axis -> XLA inserts the gradient psum
  (reduce-scatter + all-gather over ICI) automatically;
- optimizer state (and optionally params) sharded over ``dp`` on the
  largest dim when divisible = ZeRO-1, matching the reference's
  PS-partitioned optimizer state (``DistriOptimizer.scala:383-390``);
- no straggler dropping: SPMD is lockstep (documented deviation,
  SURVEY.md §7 "hard parts"); loss semantics are exact global-batch
  averages instead of the reference's ``numFinishedModelUpdates`` scaling;
- fp16 wire compression becomes a dtype policy choice (bf16 compute).

Multi-host: the same code runs under ``jax.distributed`` initialization —
collectives ride ICI within a slice and DCN across slices.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigdl_tpu.core.engine import Engine
from bigdl_tpu.optim.optimizer import Optimizer


def _check_overlap_criterion(criterion) -> None:
    """Refuse criteria the overlap step would silently mis-scale.

    The bucketed backward collectives divide psum'd cotangents by the dp
    axis size, which equals the global-batch gradient ONLY when the loss
    is an unweighted mean over local rows: a sum loss
    (``size_average=False``) needs the raw psum, and per-class weights
    need a weight-sum reduction across shards (ADVICE round 5). Walks
    wrapper criteria (``inner`` / ``criterion`` / ``criterions``) so e.g.
    ``TimeDistributedCriterion(ClassNLLCriterion(weights=w))`` cannot
    smuggle a weighted loss past the check. Combination-weight LISTS
    (Multi/ParallelCriterion) are shard-independent constants and fine;
    only per-class weight ARRAYS break the mean contract.
    """
    stack, seen = [criterion], set()
    while stack:
        c = stack.pop()
        if id(c) in seen:
            continue
        seen.add(id(c))
        name = type(c).__name__
        if getattr(c, "size_average", True) is False:
            raise ValueError(
                f"overlap_buckets requires size_average=True (mean) "
                f"criteria: {name} is a sum loss, and the bucketed "
                "collectives divide summed cotangents by the dp axis "
                "size, mis-scaling it by 1/n. Use the auto-sharded path "
                "(overlap_buckets=0) instead")
        w = getattr(c, "weights", None)
        if w is not None and not isinstance(w, (list, tuple)):
            raise ValueError(
                f"overlap_buckets requires unweighted criteria: {name} "
                "carries per-class weights, whose weighted mean "
                "normalizes by the LOCAL weight sum — dividing psum'd "
                "cotangents by the shard count does not reproduce the "
                "global weighted mean. Use the auto-sharded path "
                "(overlap_buckets=0) instead")
        for attr in ("inner", "criterion"):
            sub = getattr(c, attr, None)
            if hasattr(sub, "forward"):
                stack.append(sub)
        stack.extend(sub for sub in (getattr(c, "criterions", None) or [])
                     if hasattr(sub, "forward"))


class DistriOptimizer(Optimizer):
    def __init__(self, model, dataset, criterion, batch_size=None, config=None,
                 mesh: Optional[Mesh] = None, zero1: bool = True,
                 overlap_buckets: int = 0, overlap_wire_dtype=None):
        super().__init__(model, dataset, criterion, batch_size, config)
        self.engine = Engine.init(config)
        self.mesh = mesh or self.engine.mesh()
        # overlap mode builds an explicit shard_map step with bucketed
        # psums fired inside the backward (the reference's layer-wise
        # async sync, ParallelOptimizer.scala:481) — params and optimizer
        # state stay replicated there, so it excludes ZeRO-1 sharding
        # (use parallel.overlap.make_zero1_overlap_step for RS+AG)
        self.overlap_buckets = int(overlap_buckets)
        # wire compression for the bucketed collectives (e.g. jnp.bfloat16
        # — the reference's per-layer fp16 blocks,
        # DistriParameterSynchronizer.scala:96); None = exact fp32 wire
        if overlap_wire_dtype is not None and not self.overlap_buckets:
            raise ValueError(
                "overlap_wire_dtype only applies to the bucketed overlap "
                "step — pass overlap_buckets=K as well (the auto-sharded "
                "path's collective dtype is chosen by XLA)")
        self.overlap_wire_dtype = overlap_wire_dtype
        self.zero1 = zero1 and not self.overlap_buckets
        dp = self.config.dp_axis
        if self.batch_size % self.mesh.shape[dp] != 0:
            raise ValueError(
                f"batch size {self.batch_size} not divisible by dp={self.mesh.shape[dp]}"
            )

    def _build_step(self):
        if not self.overlap_buckets:
            return super()._build_step()
        if set(self.optim_methods) != {"__all__"}:
            raise ValueError(
                "overlap_buckets requires a single optim method (__all__)")
        _check_overlap_criterion(self.criterion)
        from bigdl_tpu.parallel.overlap import make_ddp_overlap_step

        base = make_ddp_overlap_step(
            self.model, self.criterion, self.optim_methods["__all__"],
            self.mesh, axis=self.config.dp_axis,
            num_buckets=self.overlap_buckets,
            cast_input=self.config.dtypes.cast_compute,
            grad_clip=self.grad_clip, with_rng=True,
            wire_dtype=self.overlap_wire_dtype)

        def step(params, mstate, ostates, x, y, rng, epoch):
            # adapt the shared builder to the Optimizer loop's
            # dict-of-methods state shape (single method enforced above)
            p, ms, os_, loss = base(params, mstate, ostates["__all__"],
                                    x, y, epoch, rng)
            return p, ms, {"__all__": os_}, loss

        data_sharding, _ = self._shardings()
        return jax.jit(step, donate_argnums=(0, 1, 2)), data_sharding

    def _should_write_checkpoint(self) -> bool:
        """Single-writer rule: under ``jax.distributed`` every host runs
        this driver loop, but only process 0 commits to the checkpoint
        directory — N hosts racing the same ``MANIFEST.json`` would tear
        the commit protocol. Within one host, ZeRO-1-sharded leaves are
        reassembled by the snapshot's ``np.asarray`` (all shards are
        addressable on a single-host mesh); truly multi-host sharded
        checkpoints, where no single host holds every shard, are a
        ROADMAP follow-up."""
        return jax.process_index() == 0

    def _param_spec(self, leaf) -> P:
        """ZeRO-1-style spec: shard the largest divisible dim over dp,
        replicate otherwise. Applied to params and optimizer buffers (the
        reference keeps optimizer state only on the owning PS partition)."""
        if not self.zero1 or not hasattr(leaf, "shape") or leaf.ndim == 0:
            return P()
        dp = self.config.dp_axis
        n = self.mesh.shape[dp]
        dims = list(leaf.shape)
        best = max(range(len(dims)), key=lambda i: dims[i])
        if dims[best] % n == 0 and dims[best] >= 2 * n:
            spec = [None] * len(dims)
            spec[best] = dp
            return P(*spec)
        return P()

    def _shardings(self):
        dp = self.config.dp_axis
        data_sharding = NamedSharding(self.mesh, P(dp))
        self._ensure_initialized()
        param_sharding = jax.tree_util.tree_map(
            lambda leaf: NamedSharding(self.mesh, self._param_spec(leaf)), self._params
        )
        # place initial params/state accordingly
        self._params = jax.tree_util.tree_map(
            lambda leaf, s: jax.device_put(leaf, s), self._params, param_sharding
        )
        replicated = NamedSharding(self.mesh, P())
        self._module_state = jax.tree_util.tree_map(
            lambda leaf: jax.device_put(leaf, replicated), self._module_state
        )
        self._optim_state = jax.tree_util.tree_map(
            lambda leaf: jax.device_put(
                leaf, NamedSharding(self.mesh, self._param_spec(leaf))
            ),
            self._optim_state,
        )
        return data_sharding, None  # step shardings inferred from placed args
