"""Triggers driving epochs/validation/checkpoints.

Reference: ``DL/optim/Trigger.scala:27`` — everyEpoch, severalIteration,
maxEpoch, maxIteration, maxScore, minLoss, and/or composition. A trigger is
a host-side predicate over the training ``TrainingState`` (driver state in
the reference's ``DistriOptimizer``).
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass
class TrainingState:
    """Host-side driver state (reference: the ``driverState`` Table in
    ``DistriOptimizer.optimize``)."""

    epoch: int = 1
    iteration: int = 0
    records_processed_this_epoch: int = 0
    epoch_finished: bool = False
    loss: float = float("inf")
    score: float = 0.0


class Trigger:
    def __call__(self, state: TrainingState) -> bool:
        raise NotImplementedError

    @staticmethod
    def every_epoch() -> "Trigger":
        return _EveryEpoch()

    @staticmethod
    def several_iteration(n: int) -> "Trigger":
        return _SeveralIteration(n)

    @staticmethod
    def max_epoch(n: int) -> "Trigger":
        return _MaxEpoch(n)

    @staticmethod
    def max_iteration(n: int) -> "Trigger":
        return _MaxIteration(n)

    @staticmethod
    def max_score(s: float) -> "Trigger":
        return _MaxScore(s)

    @staticmethod
    def min_loss(l: float) -> "Trigger":
        return _MinLoss(l)

    @staticmethod
    def and_(*triggers: "Trigger") -> "Trigger":
        return _And(triggers)

    @staticmethod
    def or_(*triggers: "Trigger") -> "Trigger":
        return _Or(triggers)


class _EveryEpoch(Trigger):
    def __call__(self, state):
        return state.epoch_finished


class _SeveralIteration(Trigger):
    def __init__(self, n: int):
        self.n = n

    def __call__(self, state):
        return state.iteration > 0 and state.iteration % self.n == 0


class _MaxEpoch(Trigger):
    def __init__(self, n: int):
        self.n = n

    def __call__(self, state):
        return state.epoch > self.n


class _MaxIteration(Trigger):
    def __init__(self, n: int):
        self.n = n

    def __call__(self, state):
        return state.iteration >= self.n


class _MaxScore(Trigger):
    def __init__(self, s: float):
        self.s = s

    def __call__(self, state):
        return state.score >= self.s


class _MinLoss(Trigger):
    def __init__(self, l: float):
        self.l = l

    def __call__(self, state):
        return state.loss <= self.l


class _And(Trigger):
    def __init__(self, triggers):
        self.triggers = triggers

    def __call__(self, state):
        return all(t(state) for t in self.triggers)


class _Or(Trigger):
    def __init__(self, triggers):
        self.triggers = triggers

    def __call__(self, state):
        return any(t(state) for t in self.triggers)
