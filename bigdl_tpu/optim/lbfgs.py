"""L-BFGS with Wolfe line search.

Reference: ``DL/optim/LBFGS.scala`` (two-loop recursion over an
``nCorrection``-deep (s, y) history, optional ``lswolfe`` line search from
``DL/optim/LineSearch.scala``, tolFun/tolX stopping rules).

TPU-native shape: the objective ``feval(x)`` is a jitted pure function of
a FLAT parameter vector (use ``jax.flatten_util.ravel_pytree`` to get one
from a params pytree); the outer iteration and line search are host-side
control flow exactly like the reference's driver loop — each feval is one
XLA execution.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np


def ls_wolfe(feval, x, t, d, f, g, gtd, c1=1e-4, c2=0.9, tol_x=1e-9,
             max_iter=25):
    """Strong-Wolfe cubic-interpolation line search (reference
    ``LineSearch.lswolfe``). Returns (f_new, g_new, x_new, t, n_evals)."""
    d_norm = float(jnp.abs(d).max())
    g = jnp.asarray(g)
    # bracket phase
    t_prev, f_prev, g_prev, gtd_prev = 0.0, f, g, gtd
    ls_iter = 0
    bracket = None
    while ls_iter < max_iter:
        f_new, g_new = feval(x + t * d)
        ls_iter += 1
        gtd_new = float(jnp.vdot(g_new, d))
        if f_new > f + c1 * t * gtd or (ls_iter > 1 and f_new >= f_prev):
            bracket = (t_prev, t, f_prev, f_new, g_prev, g_new, gtd_prev, gtd_new)
            break
        if abs(gtd_new) <= -c2 * gtd:
            return f_new, g_new, x + t * d, t, ls_iter
        if gtd_new >= 0:
            bracket = (t_prev, t, f_prev, f_new, g_prev, g_new, gtd_prev, gtd_new)
            break
        t_prev, f_prev, g_prev, gtd_prev = t, f_new, g_new, gtd_new
        t = t * 2.0
    else:
        return f_new, g_new, x + t * d, t, ls_iter

    # zoom phase on [lo, hi]
    t_lo, t_hi, f_lo, f_hi, g_lo, g_hi, gtd_lo, gtd_hi = bracket
    for _ in range(max_iter - ls_iter):
        # cubic interpolation (reference polyinterp); fall back to bisection
        d1 = gtd_lo + gtd_hi - 3 * (f_lo - f_hi) / (t_lo - t_hi + 1e-30)
        sq = d1 * d1 - gtd_lo * gtd_hi
        if sq >= 0:
            d2 = np.sqrt(sq) * (1.0 if t_hi >= t_lo else -1.0)
            t = t_hi - (t_hi - t_lo) * (gtd_hi + d2 - d1) / (
                gtd_hi - gtd_lo + 2 * d2 + 1e-30)
            lo, hi = min(t_lo, t_hi), max(t_lo, t_hi)
            if not (lo < t < hi):
                t = (t_lo + t_hi) / 2.0
        else:
            t = (t_lo + t_hi) / 2.0
        if abs(t_hi - t_lo) * d_norm < tol_x:
            break
        f_new, g_new = feval(x + t * d)
        ls_iter += 1
        gtd_new = float(jnp.vdot(g_new, d))
        if f_new > f + c1 * t * gtd or f_new >= f_lo:
            t_hi, f_hi, g_hi, gtd_hi = t, f_new, g_new, gtd_new
        else:
            if abs(gtd_new) <= -c2 * gtd:
                return f_new, g_new, x + t * d, t, ls_iter
            if gtd_new * (t_hi - t_lo) >= 0:
                t_hi, f_hi, g_hi, gtd_hi = t_lo, f_lo, g_lo, gtd_lo
            t_lo, f_lo, g_lo, gtd_lo = t, f_new, g_new, gtd_new
    f_new, g_new = feval(x + t_lo * d)
    return f_new, g_new, x + t_lo * d, t_lo, ls_iter + 1


class LBFGS:
    """Reference ``LBFGS.scala`` driver. ``optimize(feval, x0)`` where
    ``feval(x) -> (loss, grad)`` over a flat vector; returns (x, [f...])."""

    def __init__(self, max_iter: int = 20, max_eval: Optional[float] = None,
                 tol_fun: float = 1e-5, tol_x: float = 1e-9,
                 n_correction: int = 100, learning_rate: float = 1.0,
                 line_search: Optional[Callable] = ls_wolfe):
        self.max_iter = max_iter
        self.max_eval = max_eval if max_eval is not None else max_iter * 1.25
        self.tol_fun = tol_fun
        self.tol_x = tol_x
        self.n_correction = n_correction
        self.learning_rate = learning_rate
        self.line_search = line_search

    def optimize(self, feval, x) -> Tuple[jnp.ndarray, List[float]]:
        x = jnp.asarray(x)
        f, g = feval(x)
        f = float(f)
        fs = [f]
        n_eval = 1
        if float(jnp.abs(g).max()) <= 1e-10:  # already optimal
            return x, fs

        S: List[jnp.ndarray] = []  # param diffs
        Y: List[jnp.ndarray] = []  # grad diffs
        rho: List[float] = []
        h_diag = 1.0
        g_prev = None
        t = None

        for it in range(self.max_iter):
            # two-loop recursion: d = -H g
            if not S:
                d = -g
            else:
                q = -g
                alphas = []
                for s_i, y_i, r_i in zip(reversed(S), reversed(Y), reversed(rho)):
                    a = r_i * float(jnp.vdot(s_i, q))
                    alphas.append(a)
                    q = q - a * y_i
                q = q * h_diag
                for s_i, y_i, r_i, a in zip(S, Y, rho, reversed(alphas)):
                    b = r_i * float(jnp.vdot(y_i, q))
                    q = q + (a - b) * s_i
                d = q
            gtd = float(jnp.vdot(g, d))
            if gtd > -self.tol_x:  # not a descent direction
                break

            # step size: first iteration scales by gradient magnitude
            if it == 0:
                t = min(1.0, 1.0 / float(jnp.abs(g).sum())) * self.learning_rate
            else:
                t = self.learning_rate

            g_prev = g
            x_prev = x
            if self.line_search is not None:
                f, g, x, t, evals = self.line_search(feval, x, t, d, f, g, gtd)
                f = float(f)
                n_eval += evals
            else:
                x = x + t * d
                f, g = feval(x)
                f = float(f)
                n_eval += 1
            fs.append(f)

            s = x - x_prev
            y = g - g_prev
            ys = float(jnp.vdot(y, s))
            if ys > 1e-10:
                if len(S) == self.n_correction:
                    S.pop(0)
                    Y.pop(0)
                    rho.pop(0)
                S.append(s)
                Y.append(y)
                rho.append(1.0 / ys)
                h_diag = ys / float(jnp.vdot(y, y))

            if n_eval >= self.max_eval:
                break
            if float(jnp.abs(g).max()) <= 1e-10:
                break
            if float(jnp.abs(t * d).max()) <= self.tol_x:
                break
            if len(fs) > 1 and abs(fs[-1] - fs[-2]) < self.tol_fun:
                break
        return x, fs
