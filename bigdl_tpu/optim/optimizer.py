"""Optimizer frontend + training loop.

Reference: ``DL/optim/Optimizer.scala`` (builder :47 — ``setValidation``
:111, ``setCheckpoint`` :198, ``setOptimMethods`` :377, ``setEndWhen``
:389, gradient clipping setters :452+; factory ``Optimizer.apply`` :602
choosing ``DistriOptimizer`` vs ``LocalOptimizer``) and the optimize loops
in ``DL/optim/LocalOptimizer.scala:95`` / ``DistriOptimizer.scala:97-537``.

TPU-native redesign: there is ONE loop. The reference's local/distributed
split exists because distribution lived in Spark jobs; here the difference
is only the sharding of the compiled train step — ``LocalOptimizer`` jits
on one chip, ``DistriOptimizer`` pjits over a mesh (data-parallel batch,
optionally ZeRO-1-sharded optimizer state, mirroring the reference's
PS-partitioned optimizer state, SURVEY.md §2.3). Per-core model replicas,
gradient aggregation trees, straggler dropping and the two-Spark-jobs
protocol (§3.1) all collapse into one XLA program with collectives.
"""

from __future__ import annotations

import logging
import sys
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.core.config import EngineConfig
from bigdl_tpu.core.engine import Engine
from bigdl_tpu.dataset.dataset import AbstractDataSet
from bigdl_tpu.dataset.prefetch import device_prefetch
from bigdl_tpu.dataset.sample import MiniBatch
from bigdl_tpu.nn.module import Criterion, Module
from bigdl_tpu.optim.metrics import Metrics
from bigdl_tpu.optim.optim_method import OptimMethod, SGD
from bigdl_tpu.ckpt import CheckpointManager
from bigdl_tpu.optim.trigger import TrainingState, Trigger

log = logging.getLogger("bigdl_tpu.optim")


def _clip_constant(grads, min_v, max_v):
    return jax.tree_util.tree_map(lambda g: jnp.clip(g, min_v, max_v), grads)


def _clip_l2norm(grads, max_norm):
    """Global-norm clip (reference: ``L2NormClippingProcessor`` — needs the
    cross-partition sum; under SPMD the global norm is just the norm of the
    full gradient pytree, collectives inserted by XLA)."""
    sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in jax.tree_util.tree_leaves(grads))
    norm = jnp.sqrt(sq)
    scale = jnp.minimum(1.0, max_norm / (norm + 1e-12))
    return jax.tree_util.tree_map(lambda g: (g * scale).astype(g.dtype), grads)


class Optimizer:
    """Builder + loop. Subclasses override ``_shardings`` only."""

    def __init__(
        self,
        model: Module,
        dataset: AbstractDataSet,
        criterion: Criterion,
        batch_size: Optional[int] = None,
        config: Optional[EngineConfig] = None,
    ):
        self.model = model
        self.dataset = dataset
        self.criterion = criterion
        self.config = config or Engine.get().config
        self.batch_size = batch_size or self.config.default_batch_size
        self.optim_methods: Dict[str, OptimMethod] = {"__all__": SGD()}
        self.end_when: Trigger = Trigger.max_epoch(10)
        self.val_trigger: Optional[Trigger] = None
        self.val_dataset: Optional[AbstractDataSet] = None
        self.val_methods: Optional[List] = None
        self.val_batch_size: Optional[int] = None
        self._eval_fn = None
        self._data_sharding = None
        self.checkpoint_path: Optional[str] = None
        self.checkpoint_trigger: Optional[Trigger] = None
        self.checkpoint_manager: Optional[CheckpointManager] = None
        self._auto_resume = False
        # iteration of the last save OR restore: re-arms the checkpoint
        # trigger across a resume so a restored run doesn't immediately
        # re-save the step it just loaded
        self._last_ckpt_iteration = -1
        self.train_summary = None
        self.val_summary = None
        self.grad_clip: Optional[Callable] = None
        self.state = TrainingState()
        self.metrics = Metrics()
        self._params = None
        self._module_state = None
        self._optim_state = None
        # background host-pipeline depth (0 disables the feeder thread)
        self.host_prefetch_depth = 2
        # parallel input pipeline (0 workers = serial transformer chain)
        self.pipeline_n_workers = 0
        self.pipeline_depth = 2
        self.pipeline_ordered = True
        self.pipeline_processes = False
        self.pipeline_chunk = 1
        self.pipeline_max_restarts = 2
        self.pipeline_stats = None
        # step watchdog (set_watchdog): seconds without a completed
        # iteration before the stall handler fires; None = disabled
        self.watchdog_timeout: Optional[float] = None
        self._watchdog_on_stall: Optional[Callable] = None
        self.watchdog_error = None
        # obs tier (set_metrics_registry): the registry the per-step
        # gauges publish into, and the last-iteration values it reads
        self.obs_registry = None
        self._last_lr = 0.0
        self._last_throughput = 0.0
        self._rng = jax.random.key(self.config.seed)

    # ------------------------------------------------ builder setters ----
    def set_optim_method(self, method: OptimMethod) -> "Optimizer":
        self.optim_methods = {"__all__": method}
        return self

    def set_optim_methods(self, methods: Dict[str, OptimMethod]) -> "Optimizer":
        """Per-submodule optim methods keyed by top-level child name
        (reference: ``setOptimMethods``, multi-optim by submodule,
        ``DistriOptimizer.scala:834-854``)."""
        self.optim_methods = dict(methods)
        return self

    def set_end_when(self, trigger: Trigger) -> "Optimizer":
        self.end_when = trigger
        return self

    def set_validation(self, trigger: Trigger, dataset: AbstractDataSet,
                       methods: Sequence, batch_size: Optional[int] = None) -> "Optimizer":
        self.val_trigger = trigger
        self.val_dataset = dataset
        self.val_methods = list(methods)
        self.val_batch_size = batch_size
        return self

    def set_checkpoint(
        self,
        path: str,
        trigger: Trigger,
        *,
        async_save: bool = True,
        keep_last_n: Optional[int] = None,
        keep_every_k_steps: Optional[int] = None,
        handle_preemption: bool = False,
        auto_resume: bool = False,
    ) -> "Optimizer":
        """Checkpoint to ``path`` whenever ``trigger`` fires, through a
        :class:`~bigdl_tpu.ckpt.CheckpointManager` (async verified commits;
        ``async_save=False`` forces the legacy blocking behavior).
        ``handle_preemption`` arms SIGTERM to commit a final checkpoint at
        the next step boundary and stop cleanly; ``auto_resume`` makes
        ``optimize()`` restore the newest committed checkpoint from
        ``path`` before the first step, so a preempted-and-rescheduled job
        continues where it stopped just by rerunning the same command."""
        self.checkpoint_path = path
        self.checkpoint_trigger = trigger
        self._auto_resume = auto_resume
        if self.checkpoint_manager is not None:
            self.checkpoint_manager.close()
        self.checkpoint_manager = CheckpointManager(
            path, async_save=async_save, keep_last_n=keep_last_n,
            keep_every_k_steps=keep_every_k_steps)
        if handle_preemption:
            self.checkpoint_manager.install_preemption_hook()
        return self

    def set_data_pipeline(
        self,
        n_workers: int = 0,
        *,
        depth: int = 2,
        ordered: bool = True,
        processes: bool = False,
        chunk: int = 1,
        host_depth: Optional[int] = None,
        stats=None,
        max_worker_restarts: int = 2,
    ) -> "Optimizer":
        """Configure the parallel host input pipeline (reference analogue:
        ``MTLabeledBGRImgToBatch``'s thread pool). With ``n_workers > 0``
        and a transformed dataset, the elementwise run of the transformer
        chain fans out across a worker pool
        (:mod:`bigdl_tpu.dataset.parallel_pipeline`) with deterministic
        per-element augmentation seeds; batching/shuffle stages stay
        serial. ``host_depth`` overrides the staging-thread buffer.
        Per-stage counters land in ``self.pipeline_stats`` (a
        :class:`~bigdl_tpu.dataset.parallel_pipeline.PipelineStats`) and
        are folded into the step metrics each log interval."""
        from bigdl_tpu.dataset.parallel_pipeline import PipelineStats

        self.pipeline_n_workers = int(n_workers)
        self.pipeline_depth = depth
        self.pipeline_ordered = ordered
        self.pipeline_processes = processes
        self.pipeline_chunk = chunk
        self.pipeline_max_restarts = int(max_worker_restarts)
        if host_depth is not None:
            self.host_prefetch_depth = host_depth
        self.pipeline_stats = stats or PipelineStats()
        return self

    def set_watchdog(self, timeout: float,
                     on_stall: Optional[Callable] = None) -> "Optimizer":
        """Arm a training-step watchdog: if NO iteration completes for
        ``timeout`` seconds, ``on_stall(err)`` fires from the watchdog
        thread with a :class:`~bigdl_tpu.faults.StallError` diagnostic.
        The default handler records the error on ``watchdog_error`` and
        poisons the dataset through its ``fail()`` hook when it has one
        (``SocketFeedDataSet`` does) — so a loop blocked on a feed whose
        producers silently died surfaces the stall instead of waiting
        forever. A wedged XLA dispatch cannot be unwound from Python;
        there the watchdog still leaves a loud diagnostic in the log."""
        if timeout <= 0:
            # validate HERE, not when Watchdog is built mid-optimize():
            # 0.0 would silently disable the guard, negatives would
            # crash far from the misuse site
            raise ValueError(f"watchdog timeout must be > 0, got {timeout}")
        self.watchdog_timeout = float(timeout)
        self._watchdog_on_stall = on_stall
        return self

    def _watchdog_stalled(self, err) -> None:
        self.watchdog_error = err
        if self._watchdog_on_stall is not None:
            self._watchdog_on_stall(err)
            return
        log.error("training stalled: %s", err)
        # the fail() hook usually lives on the BASE dataset (a
        # SocketFeedDataSet wrapped by `>> transformer` layers exposes it
        # only there), so walk the wrapper chain
        ds = self.dataset
        while ds is not None:
            fail = getattr(ds, "fail", None)
            if callable(fail):
                fail(err)
                return
            ds = getattr(ds, "base", None)

    def set_metrics_registry(self, registry,
                             name: str = "train") -> "Optimizer":
        """Publish the train-side step gauges (loss / throughput /
        learning rate / iteration) into an
        :class:`~bigdl_tpu.obs.MetricsRegistry`, NEXT TO — not instead
        of — the TensorBoard summary writer: one ``collect()`` then
        surfaces training beside the serving/paging/replica/ckpt/fault
        gauges. When a parallel input pipeline or a checkpoint manager
        is configured (call this AFTER ``set_data_pipeline`` /
        ``set_checkpoint``), their per-stage rates and commit counters
        register under ``<name>.pipeline`` / ``<name>.ckpt``."""
        registry.register(name, self._obs_snapshot)
        if self.pipeline_stats is not None:
            registry.register(f"{name}.pipeline", self.pipeline_stats)
        if self.checkpoint_manager is not None:
            registry.register(f"{name}.ckpt", self.checkpoint_manager)
        self.obs_registry = registry
        return self

    def _obs_snapshot(self) -> dict:
        """Per-interval step gauges for the metrics registry."""
        return {"iteration": self.state.iteration,
                "epoch": self.state.epoch,
                "loss": self.state.loss,
                "throughput": self._last_throughput,
                "learning_rate": self._last_lr}

    def set_train_summary(self, summary) -> "Optimizer":
        self.train_summary = summary
        return self

    def set_val_summary(self, summary) -> "Optimizer":
        self.val_summary = summary
        return self

    def set_gradclip_const(self, min_v: float, max_v: float) -> "Optimizer":
        self.grad_clip = lambda g: _clip_constant(g, min_v, max_v)
        return self

    def set_gradclip_l2norm(self, max_norm: float) -> "Optimizer":
        self.grad_clip = lambda g: _clip_l2norm(g, max_norm)
        return self

    def disable_gradclip(self) -> "Optimizer":
        self.grad_clip = None
        return self

    def set_model_and_state(self, params, module_state=None, optim_state=None) -> "Optimizer":
        """Resume from externally loaded params/state."""
        self._params = params
        self._module_state = module_state
        self._optim_state = optim_state
        return self

    # ------------------------------------------------------ shardings ----
    def _shardings(self):
        """(data_sharding, param_sharding) — None means single device."""
        return None, None

    # ------------------------------------------------------- the step ----
    def _split_params(self, params):
        """Partition top-level param subtrees across optim methods. Method
        keys that match no param subtree are dropped (a parameterless
        submodule, or an unused ``__default__``) — only keys that match
        nothing at all are an error."""
        if set(self.optim_methods) == {"__all__"}:
            return {"__all__": params}
        groups: Dict[str, Dict] = {}
        default = self.optim_methods.get("__default__")
        for key in params:
            target = key if key in self.optim_methods else "__default__"
            if target == "__default__" and default is None:
                raise ValueError(
                    f"no optim method for submodule '{key}' and no '__default__' given"
                )
            groups.setdefault(target, {})[key] = params[key]
        unmatched = set(self.optim_methods) - set(groups) - {"__default__"}
        if unmatched:
            raise ValueError(
                f"optim method keys {sorted(unmatched)} match no top-level param "
                f"subtree (available: {sorted(params)})"
            )
        return groups

    def _build_step(self):
        model, criterion = self.model, self.criterion
        dtypes = self.config.dtypes
        grad_clip = self.grad_clip
        methods = self.optim_methods

        def step(params, mstate, ostates, x, y, rng, epoch):
            def loss_fn(p):
                xin = dtypes.cast_compute(x)
                out, new_mstate = model.apply(p, xin, state=mstate, training=True, rng=rng)
                out32 = jax.tree_util.tree_map(
                    lambda a: a.astype(jnp.float32)
                    if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating)
                    else a,
                    out,
                )
                return criterion.forward(out32, y), new_mstate

            (loss, new_mstate), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
            if grad_clip is not None:
                grads = grad_clip(grads)
            grad_groups = self._split_params(grads)
            param_groups = self._split_params(params)
            new_params: Dict[str, Any] = {}
            new_ostates: Dict[str, Any] = {}
            for name in grad_groups:  # only methods with matching param groups
                p_new, o_new = methods[name].update(
                    grad_groups[name], param_groups[name], ostates[name], epoch
                )
                new_ostates[name] = o_new
                if name == "__all__":
                    new_params = p_new
                else:
                    new_params.update(p_new)
            return new_params, new_mstate, new_ostates, loss

        data_sharding, _ = self._shardings()
        return jax.jit(step, donate_argnums=(0, 1, 2)), data_sharding

    def _build_eval_step(self):
        from bigdl_tpu.optim.validation import split_methods

        model = self.model
        dtypes = self.config.dtypes
        methods = self.val_methods
        jit_idx, _ = split_methods(methods)

        def eval_step(params, mstate, x, y):
            out, _ = model.apply(params, dtypes.cast_compute(x), state=mstate, training=False)
            out = jax.tree_util.tree_map(
                lambda a: a.astype(jnp.float32)
                if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating)
                else a,
                out,
            )
            # host-side (non-jit-safe) methods consume `out` after the step
            return out, [methods[i].batch(out, y) for i in jit_idx]

        return jax.jit(eval_step)

    # ------------------------------------------------------- init --------
    def _ensure_initialized(self):
        if self._params is None:
            self._rng, sub = jax.random.split(self._rng)
            self._params, self._module_state = self.model.init(sub)
        if self._module_state is None:
            self._module_state = {}
        if self._optim_state is None:
            groups = self._split_params(self._params)
            self._optim_state = {
                name: self.optim_methods[name].init_state(group)
                for name, group in groups.items()
            }

    # ------------------------------------------------------- optimize ----
    def optimize(self):
        """Run the training loop; returns (params, module_state).

        Mirrors the reference driver loop (``DistriOptimizer.scala:186-535``):
        per-iteration loss/throughput metrics, triggers for validation /
        checkpoint / summaries, epoch accounting by records processed, and
        checkpoint-based retry on failure (:881-960).
        """
        if self._auto_resume and self.checkpoint_manager is not None:
            self._auto_resume = False  # once per optimizer, not per retry
            # resume from manifest-committed entries or a legacy
            # pre-manifest directory; when nothing is restorable — empty
            # dir or every entry corrupt — reset_on_missing=False keeps
            # any set_model_and_state warm-start params
            self._restore_latest(reset_on_missing=False)
        retries = 0
        while True:
            try:
                return self._optimize_impl()
            except KeyboardInterrupt:
                raise
            except Exception:
                retries += 1
                if retries > self.config.failure_retry_times or not self.checkpoint_path:
                    raise
                log.exception(
                    "training failed; retrying from latest checkpoint (%d/%d)",
                    retries, self.config.failure_retry_times,
                )
                if self.config.failure_retry_interval_sec > 0:
                    time.sleep(self.config.failure_retry_interval_sec)
                self._restore_latest()

    def _restore_latest(self, reset_on_missing: bool = True):
        if self.checkpoint_manager is None:
            self.checkpoint_manager = CheckpointManager(self.checkpoint_path)
        self._ensure_initialized()
        restored = self.checkpoint_manager.restore_latest(
            {
                "params": self._params,
                "module_state": self._module_state,
                "optim_state": self._optim_state,
            }
        )
        if restored is None:
            # nothing restorable. On the retry path, restart fresh (the
            # reference's semantics); on auto-resume, reset_on_missing is
            # False so warm-start params survive.
            if reset_on_missing:
                self._params = None
                self._optim_state = None
                self._module_state = None
            self._last_ckpt_iteration = -1
            return
        payload, entry = restored
        self._params = payload["params"]
        self._module_state = payload["module_state"]
        self._optim_state = payload["optim_state"]
        meta = entry.meta
        self.state = TrainingState(
            epoch=meta.get("epoch", 1),
            iteration=meta.get("iteration", entry.step),
            records_processed_this_epoch=meta.get("records", 0),
        )
        # re-arm: the trigger state now points at an already-saved step
        self._last_ckpt_iteration = self.state.iteration
        log.info(
            "restored checkpoint '%s' (iteration %d, epoch %d%s)",
            entry.tag, self.state.iteration, self.state.epoch,
            ", from a preemption save" if entry.preempted else "",
        )

    def _train_batches(self):
        """Training MiniBatch stream. Array-backed datasets take the
        sliced fast path (one fancy-index gather per batch); datasets
        already composed with ``>> SampleToMiniBatch`` stream as built.
        With ``set_data_pipeline(n_workers>0)`` and a transformed dataset,
        the elementwise run of the chain fans out across the worker
        pool."""
        from bigdl_tpu.dataset.dataset import TensorDataSet, TransformedDataSet

        if isinstance(self.dataset, TensorDataSet):
            return self.dataset.batches(self.batch_size, train=True)
        if (self.pipeline_n_workers > 0
                and isinstance(self.dataset, TransformedDataSet)):
            from bigdl_tpu.dataset.parallel_pipeline import parallelize_chain

            chain = parallelize_chain(
                self.dataset.transformer,
                self.pipeline_n_workers,
                depth=self.pipeline_depth,
                ordered=self.pipeline_ordered,
                processes=self.pipeline_processes,
                chunk=self.pipeline_chunk,
                base_seed=self.config.seed,
                stats=self.pipeline_stats,
                max_worker_restarts=self.pipeline_max_restarts,
            )
            return chain.apply(self.dataset.base.data(train=True))
        return self.dataset.data(train=True)

    def _optimize_impl(self):
        self._ensure_initialized()
        step_fn, data_sharding = self._build_step()
        self._data_sharding = data_sharding
        self._eval_fn = None  # rebuilt lazily, once per optimize run
        train_size = self.dataset.size()
        batches = self._train_batches()
        state = self.state

        watchdog = None
        if self.watchdog_timeout:
            from bigdl_tpu.faults import Watchdog

            watchdog = Watchdog("optimizer", self.watchdog_timeout,
                                self._watchdog_stalled)
            watchdog.arm("training step (batch wait + compute)")
        try:
            self._train_loop(state, step_fn, data_sharding, batches,
                             train_size, watchdog)
        finally:
            if watchdog is not None:
                watchdog.close()
        if self.checkpoint_manager is not None:
            # drain in-flight async saves: once optimize() returns, every
            # triggered checkpoint is committed (and write errors surface
            # here rather than vanishing with the worker thread)
            self.checkpoint_manager.wait()
        return self._params, self._module_state

    def _train_loop(self, state, step_fn, data_sharding, batches,
                    train_size, watchdog):
        for x, y in device_prefetch(batches, data_sharding,
                                    host_depth=self.host_prefetch_depth,
                                    stats=self.pipeline_stats):
            if self.end_when(state):
                break
            t0 = time.time()
            self._rng, step_key = jax.random.split(self._rng)
            epoch_arr = jnp.asarray(state.epoch, jnp.int32)
            self._params, self._module_state, self._optim_state, loss = step_fn(
                self._params, self._module_state, self._optim_state, x, y, step_key, epoch_arr
            )
            loss = float(loss)
            bsz = int(jax.tree_util.tree_leaves(x)[0].shape[0])
            dt = time.time() - t0
            state.iteration += 1
            state.records_processed_this_epoch += bsz
            state.loss = loss
            state.epoch_finished = state.records_processed_this_epoch >= train_size
            self.metrics.set("computing time for each iteration", dt)
            self.metrics.add("throughput", bsz / max(dt, 1e-9))

            # lr actually used this iteration: schedule evaluated at the
            # pre-increment step count (optim step counter == iteration - 1
            # here since both just advanced together)
            method = next(iter(self.optim_methods.values()))
            lr = float(method.schedule(method.learning_rate, state.iteration - 1, state.epoch))
            self._last_lr = lr
            self._last_throughput = bsz / max(dt, 1e-9)
            if state.iteration % self.config.log_every_n_steps == 0:
                log.info(
                    "Epoch %d iteration %d: loss %.6f, lr %.5g. Throughput is %.1f records/second.",
                    state.epoch, state.iteration, loss, lr, bsz / max(dt, 1e-9),
                )
                if self.pipeline_stats is not None:
                    # per-stage input-pipeline gauges next to the step
                    # metrics: a starving transfer stage or a stalling
                    # augment pool shows up here, not in a profiler run
                    for sname, s in self.pipeline_stats.snapshot().items():
                        self.metrics.set(
                            f"pipeline {sname} items/s", s["items_per_sec"])
                        self.metrics.set(
                            f"pipeline {sname} stall s", s["stall_s"])
                        self.metrics.set(
                            f"pipeline {sname} starve s", s["starve_s"])
                        if s["queue_cap"]:
                            self.metrics.set(
                                f"pipeline {sname} queue occupancy",
                                s["queue_mean"] / s["queue_cap"])
            if self.train_summary is not None:
                self.train_summary.add_scalar("Loss", loss, state.iteration)
                self.train_summary.add_scalar("Throughput", bsz / max(dt, 1e-9), state.iteration)
                self.train_summary.add_scalar("LearningRate", lr, state.iteration)
                ptrig = self.train_summary.triggers.get("Parameters")
                if ptrig is not None and ptrig(state):
                    for path, leaf in self.model.parameters(self._params):
                        self.train_summary.add_histogram(path, np.asarray(leaf), state.iteration)

            if self.val_trigger is not None and self.val_trigger(state):
                self._run_validation()
            if self.checkpoint_trigger is not None and self.checkpoint_trigger(state):
                self._save_checkpoint()
            mgr = self.checkpoint_manager
            if mgr is not None and mgr.preemption_requested:
                # SIGTERM (TPU eviction) landed since the last boundary:
                # commit NOW, synchronously, and stop — the process is
                # about to die and an uncommitted async save would be lost
                log.warning(
                    "preemption requested: committing checkpoint at "
                    "iteration %d and stopping", state.iteration)
                if state.iteration == self._last_ckpt_iteration:
                    # the trigger's save of this very step may be in
                    # flight: drain it, then flip the marker with a
                    # manifest-only rewrite (no blob re-commit)
                    mgr.wait()
                    mgr.mark_preempted(f"model.iter{state.iteration}")
                else:
                    self._save_checkpoint(preempted=True, blocking=True)
                break
            if state.epoch_finished:
                state.epoch += 1
                state.records_processed_this_epoch = 0
                # re-check end condition at epoch boundary before next batch
                if self.end_when(state):
                    break
                state.epoch_finished = False
            if watchdog is not None:
                # an iteration completed end to end — validation and
                # checkpoint triggers included — so the deadline resets
                watchdog.beat()

    # ------------------------------------------------ validation ---------
    def _run_validation(self):
        from bigdl_tpu.optim.validation import (
            ValidationResult, accumulate_batch, split_methods,
        )
        from bigdl_tpu.dataset.prefetch import device_put_batch
        from bigdl_tpu.dataset.transformer import SampleToMiniBatch

        if self._eval_fn is None:
            self._eval_fn = self._build_eval_step()
        eval_fn = self._eval_fn
        data_sharding = self._data_sharding
        dp = 1
        if data_sharding is not None:
            dp = int(data_sharding.mesh.shape.get(self.config.dp_axis, 1))
        jit_idx, host_idx = split_methods(self.val_methods)
        results = [ValidationResult(0.0, 0, m.name) for m in self.val_methods]
        batch_size = self.val_batch_size or self.batch_size
        it = SampleToMiniBatch(batch_size, partial_batch=True).apply(
            self.val_dataset.data(train=False)
        )
        for batch in it:
            # a trailing partial batch may not divide the mesh: replicate it
            sharding = data_sharding if batch.size() % dp == 0 else None
            x, y = device_put_batch(batch, sharding)
            out, jit_outs = eval_fn(self._params, self._module_state, x, y)
            accumulate_batch(results, self.val_methods, jit_idx, host_idx,
                             jit_outs, out, y)
        for r in results:
            v, n = r.result()
            log.info("%s is %.6f (count %d)", r.name, v, n)
            if self.val_summary is not None:
                self.val_summary.add_scalar(r.name, v, self.state.iteration)
        self.state.score = results[0].result()[0]
        return results

    # ------------------------------------------------ checkpoint ---------
    def _should_write_checkpoint(self) -> bool:
        """Single-process default: always write. DistriOptimizer narrows
        this to one writer per job."""
        return True

    def _save_checkpoint(self, preempted: bool = False, blocking: bool = False):
        if not self._should_write_checkpoint():
            return
        if self.state.iteration == self._last_ckpt_iteration and not preempted:
            return  # this step is already on disk (e.g. just restored)
        self.checkpoint_manager.save(
            f"model.iter{self.state.iteration}",
            self._params,
            self._module_state,
            self._optim_state,
            meta={
                "epoch": self.state.epoch,
                "iteration": self.state.iteration,
                "records": self.state.records_processed_this_epoch,
                "loss": self.state.loss,
            },
            step=self.state.iteration,
            blocking=blocking,
            preempted=preempted,
        )
        self._last_ckpt_iteration = self.state.iteration


class LocalOptimizer(Optimizer):
    """Single-chip trainer (reference: ``LocalOptimizer.scala`` — its
    per-core replica threading is handled by XLA inside one chip)."""


def optimizer(model, dataset, criterion, batch_size=None, config=None) -> Optimizer:
    """Factory (reference: ``Optimizer.apply``, ``Optimizer.scala:602`` —
    picks distributed vs local by input type; here by device count)."""
    if jax.device_count() > 1:
        from bigdl_tpu.optim.distri_optimizer import DistriOptimizer

        return DistriOptimizer(model, dataset, criterion, batch_size, config)
    return LocalOptimizer(model, dataset, criterion, batch_size, config)
