"""Learning-rate schedules.

Reference: the ``LearningRateSchedule`` family inside ``DL/optim/SGD.scala:200``
(EpochSchedule, Poly, Step, MultiStep, EpochDecay, EpochStep, NaturalExp,
Exponential, Plateau :544, Warmup :599, SequentialSchedule :623,
EpochDecayWithWarmUp :671). Schedules here are pure functions of the global
step (and optionally epoch), returning the learning rate — jit-safe via
``jnp`` math so they can live inside the compiled train step.

The ResNet-50 recipe needs Warmup + Poly/MultiStep (SURVEY.md §7 phase 3).
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp


class LearningRateSchedule:
    """lr = schedule(base_lr, step, epoch). ``step`` may be a traced array."""

    def __init_subclass__(cls, **kw):
        from bigdl_tpu.nn.module import capture_init_args

        super().__init_subclass__(**kw)
        capture_init_args(cls)

    def __call__(self, base_lr, step, epoch=None):
        raise NotImplementedError


class Default(LearningRateSchedule):
    """Constant (reference SGD's default when no schedule given)."""

    def __call__(self, base_lr, step, epoch=None):
        return base_lr


class Step(LearningRateSchedule):
    """lr * gamma^(floor(step / step_size)) (reference: ``SGD.Step``)."""

    def __init__(self, step_size: int, gamma: float = 0.1):
        self.step_size = step_size
        self.gamma = gamma

    def __call__(self, base_lr, step, epoch=None):
        return base_lr * self.gamma ** jnp.floor(step / self.step_size)


class MultiStep(LearningRateSchedule):
    """Decay by gamma at each milestone step (reference: ``SGD.MultiStep``)."""

    def __init__(self, step_sizes: Sequence[int], gamma: float = 0.1):
        self.step_sizes = tuple(step_sizes)
        self.gamma = gamma

    def __call__(self, base_lr, step, epoch=None):
        milestones = jnp.asarray(self.step_sizes)
        n = jnp.sum(step >= milestones)
        return base_lr * self.gamma ** n


class Poly(LearningRateSchedule):
    """lr * (1 - step/max_steps)^power (reference: ``SGD.Poly`` — the
    ResNet-50 ImageNet recipe uses power=2)."""

    def __init__(self, power: float, max_iteration: int):
        self.power = power
        self.max_iteration = max_iteration

    def __call__(self, base_lr, step, epoch=None):
        frac = jnp.clip(step / self.max_iteration, 0.0, 1.0)
        return base_lr * (1.0 - frac) ** self.power


class Exponential(LearningRateSchedule):
    """lr * gamma^(step / decay_steps), optionally staircased
    (reference: ``SGD.Exponential``)."""

    def __init__(self, decay_step: int, decay_rate: float, staircase: bool = False):
        self.decay_step = decay_step
        self.decay_rate = decay_rate
        self.staircase = staircase

    def __call__(self, base_lr, step, epoch=None):
        p = step / self.decay_step
        if self.staircase:
            p = jnp.floor(p)
        return base_lr * self.decay_rate ** p


class NaturalExp(LearningRateSchedule):
    """lr * exp(-gamma * floor(step/decay_step)) (reference: ``SGD.NaturalExp``)."""

    def __init__(self, decay_step: int, gamma: float):
        self.decay_step = decay_step
        self.gamma = gamma

    def __call__(self, base_lr, step, epoch=None):
        return base_lr * jnp.exp(-self.gamma * jnp.floor(step / self.decay_step))


class EpochDecay(LearningRateSchedule):
    """lr * 0.1^floor(epoch / decay_epoch) (reference: ``SGD.EpochDecay``)."""

    def __init__(self, decay_epoch: int = 100):
        self.decay_epoch = decay_epoch

    def __call__(self, base_lr, step, epoch=None):
        e = 0 if epoch is None else epoch
        return base_lr * 0.1 ** jnp.floor(e / self.decay_epoch)


class EpochStep(LearningRateSchedule):
    """lr * gamma^floor(epoch / step_size) (reference: ``SGD.EpochStep``)."""

    def __init__(self, step_size: int, gamma: float):
        self.step_size = step_size
        self.gamma = gamma

    def __call__(self, base_lr, step, epoch=None):
        e = 0 if epoch is None else epoch
        return base_lr * self.gamma ** jnp.floor(e / self.step_size)


class EpochSchedule(LearningRateSchedule):
    """Piecewise-constant lr by epoch regime (reference: ``SGD.EpochSchedule``
    with ``Regime(startEpoch, endEpoch, config)``)."""

    def __init__(self, regimes: Sequence[Tuple[int, int, float]]):
        # [(start_epoch, end_epoch, lr)]
        self.regimes = list(regimes)

    def __call__(self, base_lr, step, epoch=None):
        e = 0 if epoch is None else epoch
        lr = base_lr
        for start, end, r in self.regimes:
            lr = jnp.where((e >= start) & (e <= end), r, lr)
        return lr


class Warmup(LearningRateSchedule):
    """Linear ramp base_lr -> base_lr + delta*step over warmup steps
    (reference: ``SGD.Warmup`` — used in the large-batch ResNet recipe).
    Typically wrapped in a SequentialSchedule."""

    def __init__(self, delta: float):
        self.delta = delta

    def __call__(self, base_lr, step, epoch=None):
        return base_lr + self.delta * step


class SequentialSchedule(LearningRateSchedule):
    """Chain schedules, each active for a given number of steps
    (reference: ``SGD.SequentialSchedule``)."""

    def __init__(self, schedules: Optional[List[Tuple[LearningRateSchedule, Optional[int]]]] = None):
        # accepting the chain in the constructor keeps the schedule
        # serializable via init-config capture (utils/serializer.py)
        self.schedules: List[Tuple[LearningRateSchedule, Optional[int]]] = [
            (s, n) for s, n in (schedules or [])
        ]

    def add(self, schedule: LearningRateSchedule, max_iteration: Optional[int] = None):
        self.schedules.append((schedule, max_iteration))
        return self

    def serial_config(self):
        # serialize the LIVE chain, not the constructor snapshot, so
        # schedules appended via add() survive save/load
        return (list(self.schedules),), {}

    def __call__(self, base_lr, step, epoch=None):
        lr = base_lr
        offset = 0
        result = None
        for schedule, max_it in self.schedules:
            local = step - offset
            val = schedule(base_lr, jnp.maximum(local, 0), epoch)
            if result is None:
                result = val
            else:
                result = jnp.where(step >= offset, val, result)
            if max_it is not None:
                offset += max_it
        return result if result is not None else lr


class Plateau:
    """Reduce-on-plateau (reference: ``SGD.Plateau`` at ``SGD.scala:544``).

    Stateful and metric-driven, so it runs host-side between epochs (not
    inside jit): call ``update(metric)`` and read ``.lr_factor``.
    """

    def __init__(
        self,
        monitor: str = "score",
        factor: float = 0.1,
        patience: int = 10,
        mode: str = "min",
        epsilon: float = 1e-4,
        cooldown: int = 0,
        min_lr: float = 0.0,
    ):
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.mode = mode
        self.epsilon = epsilon
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.lr_factor = 1.0
        self._best = math.inf if mode == "min" else -math.inf
        self._wait = 0
        self._cooldown_left = 0

    def better(self, a, b):
        return a < b - self.epsilon if self.mode == "min" else a > b + self.epsilon

    def update(self, metric: float, base_lr: float = 1.0) -> float:
        """Advance with a new monitored value; returns the multiplier to
        apply to ``base_lr``. ``min_lr`` floors the resulting learning rate
        itself (reference semantics), i.e. the factor never drops below
        ``min_lr / base_lr``."""
        if self._cooldown_left > 0:
            self._cooldown_left -= 1
            self._wait = 0
        if self.better(metric, self._best):
            self._best = metric
            self._wait = 0
        elif self._cooldown_left <= 0:
            self._wait += 1
            if self._wait >= self.patience:
                floor = self.min_lr / base_lr if base_lr > 0 else 0.0
                self.lr_factor = max(self.lr_factor * self.factor, floor)
                self._cooldown_left = self.cooldown
                self._wait = 0
        return self.lr_factor
