"""Inference tier: Predictor / Evaluator / PredictionService.

Reference:

- ``DL/optim/Predictor.scala:230`` + statics :35-227 — broadcast an
  eval-mode model, per-partition ``SampleToMiniBatch``, forward, then
  ``splitBatch`` (:92) back into per-sample Activities;
- ``DL/optim/Evaluator.scala:40`` — broadcast model, mapPartitions forward,
  reduce ``ValidationResult``s;
- ``DL/optim/PredictionService.scala:56`` — a blocking-queue pool of model
  instances for thread-safe concurrent single-JVM serving.

TPU-native redesign: "broadcast the model" becomes "jit-compile the forward
once" — the compiled executable is immutable and thread-safe, so the
reference's instance pool collapses to one cached executable plus a
micro-batching front door. Distribution is a sharding on the batch dim
(XLA splits the forward over chips), not an RDD.
"""

from __future__ import annotations

from typing import Any, Iterator, List, Optional, Sequence

import jax
import numpy as np

from bigdl_tpu.dataset.dataset import AbstractDataSet, DataSet
from bigdl_tpu.dataset.prefetch import device_put_batch
from bigdl_tpu.dataset.sample import MiniBatch, Sample
from bigdl_tpu.dataset.transformer import SampleToMiniBatch
from bigdl_tpu.nn.module import Module
from bigdl_tpu.optim.validation import ValidationMethod, ValidationResult


def _as_dataset(data) -> AbstractDataSet:
    if isinstance(data, AbstractDataSet):
        return data
    if isinstance(data, (list, tuple)) and data and isinstance(data[0], Sample):
        return DataSet.array(list(data))
    return DataSet.tensors(np.asarray(data))


def _split_batch(out, n: int) -> List[Any]:
    """Per-sample activities from a batched output tree
    (reference ``Predictor.splitBatch``, ``Predictor.scala:92``)."""
    leaves, treedef = jax.tree_util.tree_flatten(out)
    rows = [np.asarray(l) for l in leaves]
    return [
        jax.tree_util.tree_unflatten(treedef, [r[i] for r in rows])
        for i in range(n)
    ]


class Predictor:
    """Batched distributed/local inference (reference ``Predictor.scala``).

    ``predict`` returns a list of per-sample outputs; ``predict_class``
    argmaxes the last dim (reference ``predictClass``).
    """

    def __init__(self, model: Module, params, state=None,
                 batch_per_partition: int = 4, batch_size: Optional[int] = None):
        self.model = model
        self.params = params
        self.state = state or {}
        # reference default: batchPerPartition * nodes; here chips stand in
        self.batch_size = batch_size or batch_per_partition * max(1, jax.device_count())
        self._fwd = jax.jit(self._forward)

    def _forward(self, params, state, x):
        out, _ = self.model.apply(params, x, state=state, training=False)
        return out

    def _batches(self, data) -> Iterator[MiniBatch]:
        ds = _as_dataset(data)
        return SampleToMiniBatch(self.batch_size, partial_batch=True).apply(
            ds.data(train=False)
        )

    def predict(self, data, flatten: bool = True):
        """Forward every sample; list of per-sample output trees (or a list
        of batched outputs with ``flatten=False``)."""
        outs = []
        for batch in self._batches(data):
            x, _ = device_put_batch(batch)
            out = self._fwd(self.params, self.state, x)
            if flatten:
                outs.extend(_split_batch(out, batch.size()))
            else:
                outs.append(out)
        return outs

    def predict_class(self, data) -> np.ndarray:
        preds = self.predict(data, flatten=False)
        return np.concatenate([np.argmax(np.asarray(p), axis=-1) for p in preds])


class Evaluator:
    """Distributed model evaluation (reference ``Evaluator.scala:40``):
    forward batches, apply each ``ValidationMethod``, reduce results."""

    def __init__(self, model: Module, params, state=None,
                 batch_size: Optional[int] = None):
        self.model = model
        self.params = params
        self.state = state or {}
        self.batch_size = batch_size or 32 * max(1, jax.device_count())

    def test(self, data, methods: Sequence[ValidationMethod]) -> List[ValidationResult]:
        from bigdl_tpu.optim.validation import accumulate_batch, split_methods

        methods = list(methods)
        jit_idx, host_idx = split_methods(methods)

        @jax.jit
        def eval_step(params, state, x, y):
            out, _ = self.model.apply(params, x, state=state, training=False)
            # host-side metrics (numpy sorts/cumsums) consume the raw output
            # outside the jit; jit-safe ones reduce on device
            return out, [methods[i].batch(out, y) for i in jit_idx]

        totals = [ValidationResult(0.0, 0, m.name) for m in methods]
        ds = _as_dataset(data)
        it = SampleToMiniBatch(self.batch_size, partial_batch=True).apply(
            ds.data(train=False)
        )
        for batch in it:
            x, y = device_put_batch(batch)
            if y is None:
                raise ValueError("evaluation data must carry labels")
            out, jit_outs = eval_step(self.params, self.state, x, y)
            accumulate_batch(totals, methods, jit_idx, host_idx, jit_outs, out, y)
        return totals


class PredictionService:
    """Thread-safe concurrent inference front door
    (reference ``PredictionService.scala:56``).

    Compatibility shim over :class:`bigdl_tpu.serving.InferenceService`:
    same ``predict``/``served`` API, but concurrent callers are now
    aggregated into bucket-padded micro-batches behind one jitted forward
    instead of each running a batch-of-1 forward. The reference's
    ``instanceNumber`` model pool becomes a queue bound (``n_concurrent``
    sizes the admission-control queue): at the bound ``predict`` raises
    ``serving.Overloaded`` instead of buffering without limit.

    Contract deltas vs the old ticket pool (deliberate — backpressure is
    the point of the serving tier): a saturating burst raises
    ``Overloaded`` where the pool blocked indefinitely; a ``timeout``
    raises ``concurrent.futures.TimeoutError`` (was ``queue.Empty``) and
    the timed-out request still executes — ``served`` counts completed
    forwards, not successful ``predict`` returns.
    """

    def __init__(self, model: Module, params, state=None, n_concurrent: int = 4,
                 max_batch_size: int = 8, max_wait_ms: float = 2.0):
        if n_concurrent < 1:
            raise ValueError("n_concurrent must be >= 1")
        # lazy import: serving.batcher reuses _split_batch from this module
        from bigdl_tpu.serving import InferenceService

        self.service = InferenceService(
            model, params, state,
            max_batch_size=max_batch_size, max_wait_ms=max_wait_ms,
            max_queue=max(32, 16 * n_concurrent))

    def predict(self, x, timeout: Optional[float] = None):
        """Single-request inference: accepts one unbatched feature tree (or
        a Sample); returns the unbatched output tree."""
        if isinstance(x, Sample):
            x = x.feature
        return self.service.predict(x, timeout=timeout)

    def close(self) -> None:
        self.service.close()

    def __enter__(self) -> "PredictionService":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    @property
    def served(self) -> int:
        return self.service.metrics.served

    @property
    def metrics(self):
        return self.service.metrics
