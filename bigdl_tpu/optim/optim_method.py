"""Optimization methods.

Reference: ``DL/optim/`` — ``OptimMethod`` trait (state table +
``optimize(feval, x)``), ``SGD.scala:39`` (momentum/nesterov/dampening/
weightDecay + per-layer lr scales), ``Adam``, ``ParallelAdam`` (thread-
chunked update — on TPU that role is played by sharded optimizer state, so
``ParallelAdam`` is an alias), ``Adagrad``, ``Adadelta``, ``Adamax``,
``RMSprop``, ``Ftrl``, ``LarsSGD`` (layer-wise trust ratio).

TPU-native design: an optim method is a pure state transition

    ``new_params, new_state = method.update(grads, params, state, lr_factor)``

over pytrees, jit-safe, with the step counter inside the state so the whole
update compiles into the train step. The reference mutates a flat parameter
vector slice per PS partition (``DistriOptimizer.scala:383-390``); here
sharding of the update is decided by the trainer's pjit shardings (ZeRO-1
equivalence documented in the parallel tier).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.optim.schedules import Default, LearningRateSchedule

tmap = jax.tree_util.tree_map


class OptimMethod:
    """Base. Subclasses define ``_init_buffers`` and ``_apply``."""

    def __init_subclass__(cls, **kw):
        from bigdl_tpu.nn.module import capture_init_args

        super().__init_subclass__(**kw)
        capture_init_args(cls)

    def __init__(self, learning_rate: float = 1e-3, schedule: Optional[LearningRateSchedule] = None):
        self.learning_rate = learning_rate
        self.schedule = schedule or Default()

    # -- state --
    def init_state(self, params) -> Dict[str, Any]:
        return {"step": jnp.zeros((), jnp.int32), **self._init_buffers(params)}

    def _init_buffers(self, params) -> Dict[str, Any]:
        return {}

    # -- lr --
    def current_lr(self, state, epoch=None):
        return self.schedule(self.learning_rate, state["step"], epoch)

    # -- update --
    def update(self, grads, params, state, epoch=None, lr_factor=1.0):
        lr = self.current_lr(state, epoch) * lr_factor
        new_params, buffers = self._apply(grads, params, state, lr)
        return new_params, {**buffers, "step": state["step"] + 1}

    def _apply(self, grads, params, state, lr):
        raise NotImplementedError

    # host-side metadata for checkpointing
    def get_hyper_parameters(self) -> Dict[str, Any]:
        return {"learning_rate": self.learning_rate, "type": type(self).__name__}


def _l2(grads, params, weight_decay):
    if weight_decay == 0.0:
        return grads
    return tmap(lambda g, p: g + weight_decay * p, grads, params)


class SGD(OptimMethod):
    """Reference: ``SGD.scala:39``. momentum/dampening/nesterov/weightDecay."""

    def __init__(
        self,
        learning_rate: float = 1e-3,
        momentum: float = 0.0,
        dampening: Optional[float] = None,
        nesterov: bool = False,
        weight_decay: float = 0.0,
        schedule: Optional[LearningRateSchedule] = None,
    ):
        super().__init__(learning_rate, schedule)
        self.momentum = momentum
        self.dampening = 0.0 if dampening is None else dampening
        self.nesterov = nesterov
        self.weight_decay = weight_decay
        if nesterov and (momentum <= 0 or self.dampening != 0.0):
            raise ValueError("nesterov momentum requires momentum > 0 and zero dampening")

    def _init_buffers(self, params):
        if self.momentum == 0.0:
            return {}
        return {"velocity": tmap(jnp.zeros_like, params)}

    def _apply(self, grads, params, state, lr):
        g = _l2(grads, params, self.weight_decay)
        if self.momentum == 0.0:
            return tmap(lambda p, gi: p - lr * gi, params, g), {}
        def upd_v(v, gi):
            return self.momentum * v + (1.0 - self.dampening) * gi
        vel = tmap(upd_v, state["velocity"], g)
        if self.nesterov:
            step = tmap(lambda gi, v: gi + self.momentum * v, g, vel)
        else:
            step = vel
        return tmap(lambda p, s: p - lr * s, params, step), {"velocity": vel}


class Adam(OptimMethod):
    """Reference: ``Adam.scala`` (and ``ParallelAdam.scala`` — the chunked
    variant; chunking is replaced by sharded state under pjit)."""

    def __init__(
        self,
        learning_rate: float = 1e-3,
        beta1: float = 0.9,
        beta2: float = 0.999,
        epsilon: float = 1e-8,
        weight_decay: float = 0.0,
        schedule: Optional[LearningRateSchedule] = None,
    ):
        super().__init__(learning_rate, schedule)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.weight_decay = weight_decay

    def _init_buffers(self, params):
        return {"m": tmap(jnp.zeros_like, params), "v": tmap(jnp.zeros_like, params)}

    def _apply(self, grads, params, state, lr):
        g = _l2(grads, params, self.weight_decay)
        t = state["step"] + 1
        b1, b2 = self.beta1, self.beta2
        m = tmap(lambda mi, gi: b1 * mi + (1 - b1) * gi, state["m"], g)
        v = tmap(lambda vi, gi: b2 * vi + (1 - b2) * gi * gi, state["v"], g)
        bc1 = 1 - b1 ** t.astype(jnp.float32)
        bc2 = 1 - b2 ** t.astype(jnp.float32)
        def upd(p, mi, vi):
            mhat = mi / bc1
            vhat = vi / bc2
            return p - lr * mhat / (jnp.sqrt(vhat) + self.epsilon)
        return tmap(upd, params, m, v), {"m": m, "v": v}


ParallelAdam = Adam


class Adagrad(OptimMethod):
    def __init__(self, learning_rate: float = 1e-2, weight_decay: float = 0.0,
                 schedule: Optional[LearningRateSchedule] = None):
        super().__init__(learning_rate, schedule)
        self.weight_decay = weight_decay

    def _init_buffers(self, params):
        return {"accum": tmap(jnp.zeros_like, params)}

    def _apply(self, grads, params, state, lr):
        g = _l2(grads, params, self.weight_decay)
        accum = tmap(lambda a, gi: a + gi * gi, state["accum"], g)
        new_params = tmap(
            lambda p, gi, a: p - lr * gi / (jnp.sqrt(a) + 1e-10), params, g, accum
        )
        return new_params, {"accum": accum}


class Adadelta(OptimMethod):
    def __init__(self, decay_rate: float = 0.9, epsilon: float = 1e-10):
        super().__init__(1.0)
        self.rho = decay_rate
        self.epsilon = epsilon

    def _init_buffers(self, params):
        return {
            "accum": tmap(jnp.zeros_like, params),
            "delta_accum": tmap(jnp.zeros_like, params),
        }

    def _apply(self, grads, params, state, lr):
        rho, eps = self.rho, self.epsilon
        accum = tmap(lambda a, g: rho * a + (1 - rho) * g * g, state["accum"], grads)
        def step(g, a, d):
            return g * jnp.sqrt(d + eps) / jnp.sqrt(a + eps)
        deltas = tmap(step, grads, accum, state["delta_accum"])
        delta_accum = tmap(
            lambda d, dl: rho * d + (1 - rho) * dl * dl, state["delta_accum"], deltas
        )
        return tmap(lambda p, d: p - lr * d, params, deltas), {
            "accum": accum,
            "delta_accum": delta_accum,
        }


class Adamax(OptimMethod):
    def __init__(self, learning_rate: float = 2e-3, beta1: float = 0.9,
                 beta2: float = 0.999, epsilon: float = 1e-38):
        super().__init__(learning_rate)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def _init_buffers(self, params):
        return {"m": tmap(jnp.zeros_like, params), "u": tmap(jnp.zeros_like, params)}

    def _apply(self, grads, params, state, lr):
        b1, b2 = self.beta1, self.beta2
        t = (state["step"] + 1).astype(jnp.float32)
        m = tmap(lambda mi, g: b1 * mi + (1 - b1) * g, state["m"], grads)
        u = tmap(lambda ui, g: jnp.maximum(b2 * ui, jnp.abs(g) + self.epsilon), state["u"], grads)
        bc = 1 - b1 ** t
        return tmap(lambda p, mi, ui: p - lr / bc * mi / ui, params, m, u), {"m": m, "u": u}


class RMSprop(OptimMethod):
    def __init__(self, learning_rate: float = 1e-2, decay_rate: float = 0.99,
                 epsilon: float = 1e-8, schedule: Optional[LearningRateSchedule] = None):
        super().__init__(learning_rate, schedule)
        self.rho = decay_rate
        self.epsilon = epsilon

    def _init_buffers(self, params):
        return {"accum": tmap(jnp.zeros_like, params)}

    def _apply(self, grads, params, state, lr):
        accum = tmap(lambda a, g: self.rho * a + (1 - self.rho) * g * g, state["accum"], grads)
        new_params = tmap(
            lambda p, g, a: p - lr * g / (jnp.sqrt(a) + self.epsilon), params, grads, accum
        )
        return new_params, {"accum": accum}


class Ftrl(OptimMethod):
    """Reference: ``Ftrl.scala`` (follow-the-regularized-leader)."""

    def __init__(
        self,
        learning_rate: float = 1e-3,
        learning_rate_power: float = -0.5,
        initial_accumulator_value: float = 0.1,
        l1_regularization_strength: float = 0.0,
        l2_regularization_strength: float = 0.0,
    ):
        super().__init__(learning_rate)
        self.lr_power = learning_rate_power
        self.init_accum = initial_accumulator_value
        self.l1 = l1_regularization_strength
        self.l2 = l2_regularization_strength

    def _init_buffers(self, params):
        return {
            "accum": tmap(lambda p: jnp.full_like(p, self.init_accum), params),
            "linear": tmap(jnp.zeros_like, params),
        }

    def _apply(self, grads, params, state, lr):
        lp = self.lr_power
        accum = tmap(lambda n, g: n + g * g, state["accum"], grads)
        def upd_z(p, g, n, n_new, z):
            sigma = (n_new ** -lp - n ** -lp) / lr
            return z + g - sigma * p
        linear = tmap(upd_z, params, grads, state["accum"], accum, state["linear"])
        def upd_p(p, n_new, z_new):
            quad = n_new ** -lp / lr + 2 * self.l2
            return jnp.where(
                jnp.abs(z_new) > self.l1,
                -(z_new - jnp.sign(z_new) * self.l1) / quad,
                jnp.zeros_like(p),
            )
        p_new = tmap(upd_p, params, accum, linear)
        return p_new, {"accum": accum, "linear": linear}


class LarsSGD(OptimMethod):
    """LARS: layer-wise adaptive rate scaling (reference: ``LarsSGD.scala:47``
    — per-module trust ratio ||w|| / (||g|| + wd*||w||)). Applied per leaf
    of the params pytree, which matches per-layer granularity."""

    def __init__(
        self,
        learning_rate: float = 1e-2,
        momentum: float = 0.9,
        weight_decay: float = 5e-4,
        trust_coefficient: float = 0.001,
        schedule: Optional[LearningRateSchedule] = None,
    ):
        super().__init__(learning_rate, schedule)
        self.momentum = momentum
        self.weight_decay = weight_decay
        self.trust = trust_coefficient

    def _init_buffers(self, params):
        return {"velocity": tmap(jnp.zeros_like, params)}

    def _apply(self, grads, params, state, lr):
        def upd_v(p, g, v):
            w_norm = jnp.linalg.norm(p.astype(jnp.float32))
            g_norm = jnp.linalg.norm(g.astype(jnp.float32))
            denom = g_norm + self.weight_decay * w_norm
            ratio = jnp.where(
                (w_norm > 0) & (denom > 0), self.trust * w_norm / denom, 1.0
            )
            scaled = ratio * (g + self.weight_decay * p)
            return self.momentum * v + lr * scaled
        vel = tmap(upd_v, params, grads, state["velocity"])
        return tmap(lambda p, v: p - v, params, vel), {"velocity": vel}
