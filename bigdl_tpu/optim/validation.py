"""Validation methods and results.

Reference: ``DL/optim/ValidationMethod.scala`` — ``Top1Accuracy`` (:174),
``Top5Accuracy``, ``Loss``, ``HitRatio``, ``NDCG``, ``TreeNNAccuracy``,
plus result types with ``+`` aggregation (the reference reduces
``ValidationResult`` across executors, ``Evaluator.scala:51``). Here the
per-batch computation is jit-safe jnp math returning (value-sum, count)
pairs; aggregation is plain ``+`` on results, matching the reference's
``.reduce(_ + _)``.

Deviation: labels are 0-based (see criterion.py).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.module import Criterion


class ValidationResult:
    """(sum, count) pair with + (reference: ``AccuracyResult``/``LossResult``)."""

    def __init__(self, value: float, count: int, name: str = "result"):
        self.value = float(value)
        self.count = int(count)
        self.name = name

    def result(self):
        return (self.value / max(1, self.count), self.count)

    def __add__(self, other: "ValidationResult") -> "ValidationResult":
        assert self.name == other.name
        return ValidationResult(self.value + other.value, self.count + other.count, self.name)

    def __repr__(self):
        v, n = self.result()
        return f"{self.name}: {v:.6f} (count {n})"


class ValidationMethod:
    """Computes a per-batch (sum, count); host wraps into ValidationResult."""

    name = "method"
    #: False for metrics whose ``batch`` runs host-side numpy (sorting,
    #: cumsums) — Evaluator/KerasModel must call those OUTSIDE the jitted
    #: eval step, on materialized outputs (np.asarray on a tracer raises)
    jit_safe = True

    def batch(self, output, target):
        """Return (value_sum, count); jit-safe jnp math iff ``jit_safe``."""
        raise NotImplementedError

    def __call__(self, output, target) -> ValidationResult:
        v, n = self.batch(output, target)
        return ValidationResult(float(v), int(n), self.name)


def split_methods(methods):
    """Positional indices of jit-safe vs host-side methods. Positional (not
    name-keyed) so two metrics sharing a name accumulate separately."""
    jit_idx = [i for i, m in enumerate(methods) if m.jit_safe]
    host_idx = [i for i, m in enumerate(methods) if not m.jit_safe]
    return jit_idx, host_idx


def accumulate_batch(totals, methods, jit_idx, host_idx, jit_outs, out, y):
    """Fold one batch's metric outputs into the positional ``totals`` list.

    ``jit_outs`` are the (sum, count) pairs computed inside the jitted eval
    step for ``jit_idx``; host-side methods consume the materialized
    ``out``/``y`` here, outside any trace. Shared by Optimizer validation,
    Evaluator.test and KerasModel.evaluate.
    """
    import numpy as np

    for i, (v, n) in zip(jit_idx, jit_outs):
        totals[i] = totals[i] + ValidationResult(float(v), int(n), methods[i].name)
    if host_idx:
        out_np = jax.tree_util.tree_map(np.asarray, out)
        y_np = np.asarray(y)
        for i in host_idx:
            v, n = methods[i].batch(out_np, y_np)
            totals[i] = totals[i] + ValidationResult(float(v), int(n), methods[i].name)
    return totals


class Top1Accuracy(ValidationMethod):
    name = "Top1Accuracy"

    def batch(self, output, target):
        pred = jnp.argmax(output, axis=-1)
        t = target.astype(pred.dtype).reshape(pred.shape)
        return jnp.sum(pred == t), t.size


class Top5Accuracy(ValidationMethod):
    name = "Top5Accuracy"

    def batch(self, output, target):
        _, top5 = jax.lax.top_k(output, 5)
        t = target.astype(top5.dtype).reshape(top5.shape[:-1] + (1,))
        return jnp.sum(jnp.any(top5 == t, axis=-1)), target.size


class TopKAccuracy(ValidationMethod):
    def __init__(self, k: int):
        self.k = k
        self.name = f"Top{k}Accuracy"

    def batch(self, output, target):
        _, topk = jax.lax.top_k(output, self.k)
        t = target.astype(topk.dtype).reshape(topk.shape[:-1] + (1,))
        return jnp.sum(jnp.any(topk == t, axis=-1)), target.size


class Loss(ValidationMethod):
    """Average criterion value (reference: ``Loss`` validation method)."""

    name = "Loss"

    def __init__(self, criterion: Optional[Criterion] = None):
        if criterion is None:
            from bigdl_tpu.nn.criterion import CrossEntropyCriterion

            criterion = CrossEntropyCriterion()
        self.criterion = criterion

    def batch(self, output, target):
        n = output.shape[0]
        return self.criterion.forward(output, target) * n, n


class HitRatio(ValidationMethod):
    """HR@k for ranking (reference: ``ValidationMethod.scala`` HitRatio):
    output = scores over candidates, target row 0 is the positive item."""

    def __init__(self, k: int = 10, neg_num: int = 100):
        self.k = k
        self.neg = neg_num
        self.name = f"HitRatio@{k}"

    def batch(self, output, target):
        # output (B, n_candidates) scores; positive is column 0
        rank = jnp.sum(output > output[:, :1], axis=-1)
        return jnp.sum(rank < self.k), output.shape[0]


class NDCG(ValidationMethod):
    """NDCG@k with a single positive at column 0 (reference: ``NDCG``)."""

    def __init__(self, k: int = 10, neg_num: int = 100):
        self.k = k
        self.neg = neg_num
        self.name = f"NDCG@{k}"

    def batch(self, output, target):
        rank = jnp.sum(output > output[:, :1], axis=-1)
        gain = jnp.where(rank < self.k, 1.0 / jnp.log2(rank.astype(jnp.float32) + 2.0), 0.0)
        return jnp.sum(gain), output.shape[0]


class PrecisionRecallAUC(ValidationMethod):
    """Area under the precision-recall curve for binary scores
    (reference: ``PrecisionRecallAUC.scala``). Host-side accumulation:
    ``batch`` collects (scores, labels); ``result`` on the accumulated
    ValidationResult is not used — call :meth:`compute` over all batches,
    or use through ``Evaluator`` which sums the streamed trapezoid areas
    per batch (approximation documented). Host-side: Evaluator applies it
    outside the jitted step (``jit_safe = False``)."""

    name = "PrecisionRecallAUC"
    jit_safe = False

    def batch(self, output, target):
        import numpy as np

        scores = np.asarray(output).reshape(-1)
        labels = np.asarray(target).reshape(-1)
        return float(self.compute(scores, labels)) * scores.size, scores.size

    @staticmethod
    def compute(scores, labels):
        import numpy as np

        order = np.argsort(-scores)
        labels = np.asarray(labels)[order]
        tp = np.cumsum(labels)
        fp = np.cumsum(1 - labels)
        total_pos = max(tp[-1], 1e-12) if len(tp) else 1e-12
        precision = tp / np.maximum(tp + fp, 1e-12)
        recall = tp / total_pos
        # prepend the recall-0 point so the first segment counts
        precision = np.concatenate([[precision[0] if len(precision) else 1.0], precision])
        recall = np.concatenate([[0.0], recall])
        return float(np.trapz(precision, recall))


class TreeNNAccuracy(ValidationMethod):
    """Accuracy on the root prediction of a tree output (reference:
    ``TreeNNAccuracy`` — used by TreeLSTM sentiment): output
    (B, n_nodes, n_classes), root is node 0."""

    name = "TreeNNAccuracy"

    def batch(self, output, target):
        root = output[:, 0] if output.ndim == 3 else output
        pred = jnp.argmax(root, axis=-1)
        t = target[:, 0] if target.ndim == 2 else target
        return jnp.sum(pred == t.astype(pred.dtype)), root.shape[0]


class MeanAveragePrecision(ValidationMethod):
    """Classification mAP over k classes (reference:
    ``MeanAveragePrecision``, ``ValidationMethod.scala:231``): average of
    per-class average precision, one-vs-rest by predicted score."""

    jit_safe = False

    def __init__(self, k: int):
        self.k = k
        self.name = "MAP@" + str(k)

    def batch(self, output, target):
        import numpy as np

        scores = np.asarray(output)
        labels = np.asarray(target).astype(int)
        aps = []
        for c in range(self.k):
            s = scores[:, c]
            y = (labels == c).astype(np.float64)
            if y.sum() == 0:
                continue
            order = np.argsort(-s)
            y = y[order]
            tp = np.cumsum(y)
            precision = tp / (np.arange(len(y)) + 1)
            ap = float((precision * y).sum() / max(y.sum(), 1))
            aps.append(ap)
        mean_ap = float(np.mean(aps)) if aps else 0.0
        n = scores.shape[0]
        return mean_ap * n, n


def _ap_from_records(records, n_gt, use_voc2007=False):
    """Average precision from (score, is_tp) match records against n_gt
    ground truths; None when undefined (no records or no gts). The single
    shared AP arithmetic for the PASCAL and COCO paths."""
    import numpy as np

    if not records or n_gt == 0:
        return None
    records = sorted(records, key=lambda r: -r[0])
    tps = np.asarray([r[1] for r in records])
    tp = np.cumsum(tps)
    fp = np.cumsum(1 - tps)
    recall = tp / n_gt
    precision = tp / np.maximum(tp + fp, 1e-12)
    if use_voc2007:
        ap = 0.0
        for t in np.arange(0.0, 1.1, 0.1):
            p = precision[recall >= t].max() if (recall >= t).any() else 0.0
            ap += p / 11
        return float(ap)
    # VOC2010+/COCO-style: area under the monotone precision envelope,
    # with (0, p) and (1, 0) sentinels so every recall segment counts
    mrec = np.concatenate([[0.0], recall, [1.0]])
    mpre = np.concatenate([[0.0], precision, [0.0]])
    for i in range(len(mpre) - 2, -1, -1):
        mpre[i] = max(mpre[i], mpre[i + 1])
    idx = np.where(mrec[1:] != mrec[:-1])[0]
    return float(np.sum((mrec[idx + 1] - mrec[idx]) * mpre[idx + 1]))


def detection_average_precision(detections, groundtruths, iou_threshold=0.5,
                                use_voc2007=False):
    """AP for one class of detections over a dataset (reference:
    ``MeanAveragePrecisionObjectDetection``, ``ValidationMethod.scala:675``).

    ``detections``: list per-image of (boxes (N,4), scores (N,));
    ``groundtruths``: list per-image of boxes (M,4). Host-side numpy.
    """
    import numpy as np

    def np_iou(a, b):
        area_a = np.maximum(a[:, 2] - a[:, 0], 0) * np.maximum(a[:, 3] - a[:, 1], 0)
        area_b = np.maximum(b[:, 2] - b[:, 0], 0) * np.maximum(b[:, 3] - b[:, 1], 0)
        lt = np.maximum(a[:, None, :2], b[None, :, :2])
        rb = np.minimum(a[:, None, 2:], b[None, :, 2:])
        wh = np.maximum(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / np.maximum(area_a[:, None] + area_b[None, :] - inter, 1e-9)

    records = []  # (score, is_tp)
    total_gt = 0
    for (boxes, scores), gt in zip(detections, groundtruths):
        boxes = np.asarray(boxes).reshape(-1, 4)
        scores = np.asarray(scores).reshape(-1)
        gt = np.asarray(gt).reshape(-1, 4)
        total_gt += len(gt)
        if len(boxes) == 0:
            continue
        if len(gt) == 0:
            records.extend((s, 0.0) for s in scores)
            continue
        iou = np_iou(boxes, gt)  # one (N, M) matrix per image, pure numpy
        taken = np.zeros(len(gt), bool)
        for i in np.argsort(-scores):
            j = int(np.argmax(iou[i]))
            if iou[i, j] >= iou_threshold and not taken[j]:
                taken[j] = True
                records.append((scores[i], 1.0))
            else:
                records.append((scores[i], 0.0))
    ap = _ap_from_records(records, total_gt, use_voc2007)
    return 0.0 if ap is None else ap


def mask_iou(masks_a, masks_b):
    """Pairwise IoU between binary mask stacks (N, H, W) x (M, H, W)
    (reference ``MaskUtils.scala``; numpy host-side like the box path).
    Intersections via one (N, P) @ (P, M) matmul — no (N, M, P) temporary,
    so full-image masks stay cheap."""
    import numpy as np

    inter, area_a, area_b = _mask_inter_areas(masks_a, masks_b)
    union = area_a[:, None] + area_b[None, :] - inter
    return inter / np.maximum(union, 1e-9)


def _mask_inter_areas(masks_a, masks_b):
    """(inter (N, M), area_a (N,), area_b (M,)) for binary mask stacks."""
    import numpy as np

    a = np.stack([np.asarray(m, bool).reshape(-1) for m in masks_a])
    b = np.stack([np.asarray(m, bool).reshape(-1) for m in masks_b])
    inter = a.astype(np.float64) @ b.astype(np.float64).T
    return inter, a.sum(-1).astype(np.float64), b.sum(-1).astype(np.float64)


COCO_IOU_THRESHOLDS = tuple(round(0.5 + 0.05 * i, 2) for i in range(10))


def _coco_pair_overlap(det, gt, order, gi, crowd, masks, d_m=None, g_m=None):
    """(len(order), len(gi)) effective-overlap matrix: standard IoU against
    normal ground truths, intersection-over-DETECTION-area against iscrowd
    ones (the COCO crowd rule)."""
    import numpy as np

    if masks:
        inter, area_d, area_g = _mask_inter_areas(
            [d_m[i] for i in order], [g_m[j] for j in gi])
    else:
        a = np.asarray(det["boxes"], np.float64).reshape(-1, 4)[order]
        b = np.asarray(gt["boxes"], np.float64).reshape(-1, 4)[gi]
        area_d = np.maximum(a[:, 2] - a[:, 0], 0) * np.maximum(a[:, 3] - a[:, 1], 0)
        area_g = np.maximum(b[:, 2] - b[:, 0], 0) * np.maximum(b[:, 3] - b[:, 1], 0)
        lt = np.maximum(a[:, None, :2], b[None, :, :2])
        rb = np.minimum(a[:, None, 2:], b[None, :, 2:])
        wh = np.maximum(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
    union = np.maximum(area_d[:, None] + area_g[None, :] - inter, 1e-9)
    iou = inter / union
    ioa = inter / np.maximum(area_d[:, None], 1e-9)
    return np.where(crowd[gi][None, :], ioa, iou)


def _coco_accumulate(records, total_gt, det, gt, iou_thresholds, masks):
    """Fold one image's detections into the (class, threshold) record
    lists. ``records[(c, t)]`` collects (score, is_tp); crowd matches are
    dropped (neither TP nor FP), crowd gts don't count as missable."""
    import numpy as np

    from bigdl_tpu.dataset.segmentation import rle_decode

    def to_mask(m):
        return rle_decode(m) if isinstance(m, dict) else np.asarray(m, bool)

    d_scores = np.asarray(det["scores"], np.float64).reshape(-1)
    d_labels = np.asarray(det["labels"]).reshape(-1).astype(int)
    g_labels = np.asarray(gt["labels"]).reshape(-1).astype(int)
    n_classes = len(total_gt)
    all_labels = np.concatenate([d_labels, g_labels])
    if all_labels.size and (all_labels.min() < 0
                            or all_labels.max() >= n_classes):
        bad = int(all_labels.min() if all_labels.min() < 0
                  else all_labels.max())
        raise ValueError(
            f"label {bad} outside [0, {n_classes}); labels must be "
            "contiguous 0-based (COCODataset.cat_to_label remaps sparse "
            "COCO category ids)")
    crowd = np.asarray(gt.get("iscrowd", np.zeros(len(g_labels))),
                       bool).reshape(-1)
    d_m = [to_mask(m) for m in det["masks"]] if masks else None
    g_m = [to_mask(m) for m in gt["masks"]] if masks else None

    for c in np.unique(np.concatenate([d_labels, g_labels])):
        di = np.where(d_labels == c)[0]
        gi = np.where(g_labels == c)[0]
        total_gt[int(c)] += int((~crowd[gi]).sum())
        if len(di) == 0:
            continue
        order = di[np.argsort(-d_scores[di])]
        if len(gi) == 0:
            for t in iou_thresholds:
                records[(int(c), t)].extend(
                    (d_scores[i], 0.0) for i in order)
            continue
        ov = _coco_pair_overlap(det, gt, order, gi, crowd, masks, d_m, g_m)
        g_crowd = crowd[gi]
        for t in iou_thresholds:
            taken = np.zeros(len(gi), bool)
            rec = records[(int(c), t)]
            for r, i in enumerate(order):
                # prefer the best still-free non-crowd gt (COCO rule)
                cand = np.where(~taken & ~g_crowd)[0]
                j = cand[np.argmax(ov[r, cand])] if len(cand) else -1
                if j >= 0 and ov[r, j] >= t:
                    taken[j] = True
                    rec.append((d_scores[i], 1.0))
                elif g_crowd.any() and ov[r, g_crowd].max(initial=0.0) >= t:
                    pass  # overlaps a crowd region: ignored, not a FP
                else:
                    rec.append((d_scores[i], 0.0))


def _coco_summarize(records, total_gt, num_classes, iou_thresholds):
    import numpy as np

    aps = []
    for c in range(num_classes):
        if total_gt[c] == 0:
            continue
        per_t = [_ap_from_records(records[(c, t)], total_gt[c])
                 for t in iou_thresholds]
        per_t = [a if a is not None else 0.0 for a in per_t]
        aps.append(float(np.mean(per_t)))
    return float(np.mean(aps)) if aps else 0.0


def coco_detection_map(detections, groundtruths, num_classes,
                       iou_thresholds=COCO_IOU_THRESHOLDS, masks=False):
    """COCO-style mAP@[.5:.95] (reference
    ``MeanAveragePrecisionObjectDetection``, ``ValidationMethod.scala:675``,
    COCO branch incl. RLE masks): per-class AP averaged over classes and
    over the 10 IoU thresholds. Crowd ground truths follow the COCO rule:
    overlap against them is intersection-over-detection-area, matches are
    ignored (neither TP nor FP), and they are not missable GTs.

    ``detections``: per image dict with keys ``boxes (N,4)``, ``scores
    (N,)``, ``labels (N,)`` and (``masks=True``) ``masks`` — list of N
    binary (H, W) arrays or RLE dicts (``dataset/segmentation.py``).
    ``groundtruths``: per image dict with ``boxes (M,4)``, ``labels (M,)``,
    optional ``iscrowd (M,)`` and ``masks``.
    Returns the scalar mAP.
    """
    import numpy as np

    records = {(c, t): [] for c in range(num_classes) for t in iou_thresholds}
    total_gt = np.zeros((num_classes,), np.int64)
    for det, gt in zip(detections, groundtruths):
        _coco_accumulate(records, total_gt, det, gt, iou_thresholds, masks)
    return _coco_summarize(records, total_gt, num_classes, iou_thresholds)


class MeanAveragePrecisionObjectDetection(ValidationMethod):
    """Detection mAP validation method (reference
    ``MeanAveragePrecisionObjectDetection``, ``ValidationMethod.scala:675``).
    ``iou_thresholds=(0.5,)`` gives PASCAL-style AP@0.5; the default COCO
    range gives mAP@[.5:.95]; ``masks=True`` scores segmentation (mask
    IoU) instead of boxes.

    Match records pool across ``batch`` calls (the reference merges raw
    records through ValidationResult ``+``), and each call returns a
    telescoping partial sum, so the framework's weighted average equals
    the pooled whole-dataset mAP regardless of batch size."""

    jit_safe = False

    def __init__(self, num_classes: int,
                 iou_thresholds=COCO_IOU_THRESHOLDS, masks: bool = False,
                 name: str = None):
        import numpy as np

        self.num_classes = num_classes
        self.iou_thresholds = tuple(iou_thresholds)
        self.masks = masks
        self.name = name or (
            "MaskMAP@[.5:.95]" if masks else "MAP@[%.2f:%.2f]" %
            (self.iou_thresholds[0], self.iou_thresholds[-1]))
        self._records = {(c, t): [] for c in range(num_classes)
                         for t in self.iou_thresholds}
        self._total_gt = np.zeros((num_classes,), np.int64)
        self._prev_sum = 0.0
        self._n_seen = 0

    def batch(self, output, target):
        """Re-summarizing every call makes a full validation epoch
        O(batches x records log records) host-side; for very large sets
        prefer one batch() call over the whole prediction list."""
        import numpy as np

        before = (sum(len(r) for r in self._records.values()),
                  int(self._total_gt.sum()))
        for det, gt in zip(output, target):
            _coco_accumulate(self._records, self._total_gt, det, gt,
                             self.iou_thresholds, self.masks)
        self._n_seen += len(output)
        after = (sum(len(r) for r in self._records.values()),
                 int(self._total_gt.sum()))
        if after == before and self._n_seen != len(output):
            pooled = self._prev_sum / max(self._n_seen - len(output), 1)
        else:
            pooled = _coco_summarize(self._records, self._total_gt,
                                     self.num_classes, self.iou_thresholds)
        contribution = pooled * self._n_seen - self._prev_sum
        self._prev_sum = pooled * self._n_seen
        return contribution, len(output)
