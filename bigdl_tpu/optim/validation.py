"""Validation methods and results.

Reference: ``DL/optim/ValidationMethod.scala`` — ``Top1Accuracy`` (:174),
``Top5Accuracy``, ``Loss``, ``HitRatio``, ``NDCG``, ``TreeNNAccuracy``,
plus result types with ``+`` aggregation (the reference reduces
``ValidationResult`` across executors, ``Evaluator.scala:51``). Here the
per-batch computation is jit-safe jnp math returning (value-sum, count)
pairs; aggregation is plain ``+`` on results, matching the reference's
``.reduce(_ + _)``.

Deviation: labels are 0-based (see criterion.py).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.module import Criterion


class ValidationResult:
    """(sum, count) pair with + (reference: ``AccuracyResult``/``LossResult``)."""

    def __init__(self, value: float, count: int, name: str = "result"):
        self.value = float(value)
        self.count = int(count)
        self.name = name

    def result(self):
        return (self.value / max(1, self.count), self.count)

    def __add__(self, other: "ValidationResult") -> "ValidationResult":
        assert self.name == other.name
        return ValidationResult(self.value + other.value, self.count + other.count, self.name)

    def __repr__(self):
        v, n = self.result()
        return f"{self.name}: {v:.6f} (count {n})"


class ValidationMethod:
    """Computes a per-batch (sum, count); host wraps into ValidationResult."""

    name = "method"
    #: False for metrics whose ``batch`` runs host-side numpy (sorting,
    #: cumsums) — Evaluator/KerasModel must call those OUTSIDE the jitted
    #: eval step, on materialized outputs (np.asarray on a tracer raises)
    jit_safe = True

    def batch(self, output, target):
        """Return (value_sum, count); jit-safe jnp math iff ``jit_safe``."""
        raise NotImplementedError

    def __call__(self, output, target) -> ValidationResult:
        v, n = self.batch(output, target)
        return ValidationResult(float(v), int(n), self.name)


def split_methods(methods):
    """Positional indices of jit-safe vs host-side methods. Positional (not
    name-keyed) so two metrics sharing a name accumulate separately."""
    jit_idx = [i for i, m in enumerate(methods) if m.jit_safe]
    host_idx = [i for i, m in enumerate(methods) if not m.jit_safe]
    return jit_idx, host_idx


def accumulate_batch(totals, methods, jit_idx, host_idx, jit_outs, out, y):
    """Fold one batch's metric outputs into the positional ``totals`` list.

    ``jit_outs`` are the (sum, count) pairs computed inside the jitted eval
    step for ``jit_idx``; host-side methods consume the materialized
    ``out``/``y`` here, outside any trace. Shared by Optimizer validation,
    Evaluator.test and KerasModel.evaluate.
    """
    import numpy as np

    for i, (v, n) in zip(jit_idx, jit_outs):
        totals[i] = totals[i] + ValidationResult(float(v), int(n), methods[i].name)
    if host_idx:
        out_np = jax.tree_util.tree_map(np.asarray, out)
        y_np = np.asarray(y)
        for i in host_idx:
            v, n = methods[i].batch(out_np, y_np)
            totals[i] = totals[i] + ValidationResult(float(v), int(n), methods[i].name)
    return totals


class Top1Accuracy(ValidationMethod):
    name = "Top1Accuracy"

    def batch(self, output, target):
        pred = jnp.argmax(output, axis=-1)
        t = target.astype(pred.dtype).reshape(pred.shape)
        return jnp.sum(pred == t), t.size


class Top5Accuracy(ValidationMethod):
    name = "Top5Accuracy"

    def batch(self, output, target):
        _, top5 = jax.lax.top_k(output, 5)
        t = target.astype(top5.dtype).reshape(top5.shape[:-1] + (1,))
        return jnp.sum(jnp.any(top5 == t, axis=-1)), target.size


class TopKAccuracy(ValidationMethod):
    def __init__(self, k: int):
        self.k = k
        self.name = f"Top{k}Accuracy"

    def batch(self, output, target):
        _, topk = jax.lax.top_k(output, self.k)
        t = target.astype(topk.dtype).reshape(topk.shape[:-1] + (1,))
        return jnp.sum(jnp.any(topk == t, axis=-1)), target.size


class Loss(ValidationMethod):
    """Average criterion value (reference: ``Loss`` validation method)."""

    name = "Loss"

    def __init__(self, criterion: Optional[Criterion] = None):
        if criterion is None:
            from bigdl_tpu.nn.criterion import CrossEntropyCriterion

            criterion = CrossEntropyCriterion()
        self.criterion = criterion

    def batch(self, output, target):
        n = output.shape[0]
        return self.criterion.forward(output, target) * n, n


class HitRatio(ValidationMethod):
    """HR@k for ranking (reference: ``ValidationMethod.scala`` HitRatio):
    output = scores over candidates, target row 0 is the positive item."""

    def __init__(self, k: int = 10, neg_num: int = 100):
        self.k = k
        self.neg = neg_num
        self.name = f"HitRatio@{k}"

    def batch(self, output, target):
        # output (B, n_candidates) scores; positive is column 0
        rank = jnp.sum(output > output[:, :1], axis=-1)
        return jnp.sum(rank < self.k), output.shape[0]


class NDCG(ValidationMethod):
    """NDCG@k with a single positive at column 0 (reference: ``NDCG``)."""

    def __init__(self, k: int = 10, neg_num: int = 100):
        self.k = k
        self.neg = neg_num
        self.name = f"NDCG@{k}"

    def batch(self, output, target):
        rank = jnp.sum(output > output[:, :1], axis=-1)
        gain = jnp.where(rank < self.k, 1.0 / jnp.log2(rank.astype(jnp.float32) + 2.0), 0.0)
        return jnp.sum(gain), output.shape[0]


class PrecisionRecallAUC(ValidationMethod):
    """Area under the precision-recall curve for binary scores
    (reference: ``PrecisionRecallAUC.scala``). Host-side accumulation:
    ``batch`` collects (scores, labels); ``result`` on the accumulated
    ValidationResult is not used — call :meth:`compute` over all batches,
    or use through ``Evaluator`` which sums the streamed trapezoid areas
    per batch (approximation documented). Host-side: Evaluator applies it
    outside the jitted step (``jit_safe = False``)."""

    name = "PrecisionRecallAUC"
    jit_safe = False

    def batch(self, output, target):
        import numpy as np

        scores = np.asarray(output).reshape(-1)
        labels = np.asarray(target).reshape(-1)
        return float(self.compute(scores, labels)) * scores.size, scores.size

    @staticmethod
    def compute(scores, labels):
        import numpy as np

        order = np.argsort(-scores)
        labels = np.asarray(labels)[order]
        tp = np.cumsum(labels)
        fp = np.cumsum(1 - labels)
        total_pos = max(tp[-1], 1e-12) if len(tp) else 1e-12
        precision = tp / np.maximum(tp + fp, 1e-12)
        recall = tp / total_pos
        # prepend the recall-0 point so the first segment counts
        precision = np.concatenate([[precision[0] if len(precision) else 1.0], precision])
        recall = np.concatenate([[0.0], recall])
        return float(np.trapz(precision, recall))


class TreeNNAccuracy(ValidationMethod):
    """Accuracy on the root prediction of a tree output (reference:
    ``TreeNNAccuracy`` — used by TreeLSTM sentiment): output
    (B, n_nodes, n_classes), root is node 0."""

    name = "TreeNNAccuracy"

    def batch(self, output, target):
        root = output[:, 0] if output.ndim == 3 else output
        pred = jnp.argmax(root, axis=-1)
        t = target[:, 0] if target.ndim == 2 else target
        return jnp.sum(pred == t.astype(pred.dtype)), root.shape[0]


class MeanAveragePrecision(ValidationMethod):
    """Classification mAP over k classes (reference:
    ``MeanAveragePrecision``, ``ValidationMethod.scala:231``): average of
    per-class average precision, one-vs-rest by predicted score."""

    jit_safe = False

    def __init__(self, k: int):
        self.k = k
        self.name = "MAP@" + str(k)

    def batch(self, output, target):
        import numpy as np

        scores = np.asarray(output)
        labels = np.asarray(target).astype(int)
        aps = []
        for c in range(self.k):
            s = scores[:, c]
            y = (labels == c).astype(np.float64)
            if y.sum() == 0:
                continue
            order = np.argsort(-s)
            y = y[order]
            tp = np.cumsum(y)
            precision = tp / (np.arange(len(y)) + 1)
            ap = float((precision * y).sum() / max(y.sum(), 1))
            aps.append(ap)
        mean_ap = float(np.mean(aps)) if aps else 0.0
        n = scores.shape[0]
        return mean_ap * n, n


def detection_average_precision(detections, groundtruths, iou_threshold=0.5,
                                use_voc2007=False):
    """AP for one class of detections over a dataset (reference:
    ``MeanAveragePrecisionObjectDetection``, ``ValidationMethod.scala:675``).

    ``detections``: list per-image of (boxes (N,4), scores (N,));
    ``groundtruths``: list per-image of boxes (M,4). Host-side numpy.
    """
    import numpy as np

    def np_iou(a, b):
        area_a = np.maximum(a[:, 2] - a[:, 0], 0) * np.maximum(a[:, 3] - a[:, 1], 0)
        area_b = np.maximum(b[:, 2] - b[:, 0], 0) * np.maximum(b[:, 3] - b[:, 1], 0)
        lt = np.maximum(a[:, None, :2], b[None, :, :2])
        rb = np.minimum(a[:, None, 2:], b[None, :, 2:])
        wh = np.maximum(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / np.maximum(area_a[:, None] + area_b[None, :] - inter, 1e-9)

    records = []  # (score, is_tp)
    total_gt = 0
    for (boxes, scores), gt in zip(detections, groundtruths):
        boxes = np.asarray(boxes).reshape(-1, 4)
        scores = np.asarray(scores).reshape(-1)
        gt = np.asarray(gt).reshape(-1, 4)
        total_gt += len(gt)
        if len(boxes) == 0:
            continue
        if len(gt) == 0:
            records.extend((s, 0.0) for s in scores)
            continue
        iou = np_iou(boxes, gt)  # one (N, M) matrix per image, pure numpy
        taken = np.zeros(len(gt), bool)
        for i in np.argsort(-scores):
            j = int(np.argmax(iou[i]))
            if iou[i, j] >= iou_threshold and not taken[j]:
                taken[j] = True
                records.append((scores[i], 1.0))
            else:
                records.append((scores[i], 0.0))
    if not records or total_gt == 0:
        return 0.0
    records.sort(key=lambda r: -r[0])
    tps = np.asarray([r[1] for r in records])
    tp = np.cumsum(tps)
    fp = np.cumsum(1 - tps)
    recall = tp / total_gt
    precision = tp / np.maximum(tp + fp, 1e-12)
    if use_voc2007:
        ap = 0.0
        for t in np.arange(0.0, 1.1, 0.1):
            p = precision[recall >= t].max() if (recall >= t).any() else 0.0
            ap += p / 11
        return float(ap)
    # VOC2010+/COCO-style: area under the monotone precision envelope,
    # with (0, p) and (1, 0) sentinels so every recall segment counts
    mrec = np.concatenate([[0.0], recall, [1.0]])
    mpre = np.concatenate([[0.0], precision, [0.0]])
    for i in range(len(mpre) - 2, -1, -1):
        mpre[i] = max(mpre[i], mpre[i + 1])
    idx = np.where(mrec[1:] != mrec[:-1])[0]
    return float(np.sum((mrec[idx + 1] - mrec[idx]) * mpre[idx + 1]))
