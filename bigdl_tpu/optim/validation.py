"""Validation methods and results.

Reference: ``DL/optim/ValidationMethod.scala`` — ``Top1Accuracy`` (:174),
``Top5Accuracy``, ``Loss``, ``HitRatio``, ``NDCG``, ``TreeNNAccuracy``,
plus result types with ``+`` aggregation (the reference reduces
``ValidationResult`` across executors, ``Evaluator.scala:51``). Here the
per-batch computation is jit-safe jnp math returning (value-sum, count)
pairs; aggregation is plain ``+`` on results, matching the reference's
``.reduce(_ + _)``.

Deviation: labels are 0-based (see criterion.py).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.module import Criterion


class ValidationResult:
    """(sum, count) pair with + (reference: ``AccuracyResult``/``LossResult``)."""

    def __init__(self, value: float, count: int, name: str = "result"):
        self.value = float(value)
        self.count = int(count)
        self.name = name

    def result(self):
        return (self.value / max(1, self.count), self.count)

    def __add__(self, other: "ValidationResult") -> "ValidationResult":
        assert self.name == other.name
        return ValidationResult(self.value + other.value, self.count + other.count, self.name)

    def __repr__(self):
        v, n = self.result()
        return f"{self.name}: {v:.6f} (count {n})"


class ValidationMethod:
    """Computes a per-batch (sum, count); host wraps into ValidationResult."""

    name = "method"

    def batch(self, output, target):
        """Return (value_sum, count) as jnp scalars — jit-safe."""
        raise NotImplementedError

    def __call__(self, output, target) -> ValidationResult:
        v, n = self.batch(output, target)
        return ValidationResult(float(v), int(n), self.name)


class Top1Accuracy(ValidationMethod):
    name = "Top1Accuracy"

    def batch(self, output, target):
        pred = jnp.argmax(output, axis=-1)
        t = target.astype(pred.dtype).reshape(pred.shape)
        return jnp.sum(pred == t), t.size


class Top5Accuracy(ValidationMethod):
    name = "Top5Accuracy"

    def batch(self, output, target):
        _, top5 = jax.lax.top_k(output, 5)
        t = target.astype(top5.dtype).reshape(top5.shape[:-1] + (1,))
        return jnp.sum(jnp.any(top5 == t, axis=-1)), target.size


class TopKAccuracy(ValidationMethod):
    def __init__(self, k: int):
        self.k = k
        self.name = f"Top{k}Accuracy"

    def batch(self, output, target):
        _, topk = jax.lax.top_k(output, self.k)
        t = target.astype(topk.dtype).reshape(topk.shape[:-1] + (1,))
        return jnp.sum(jnp.any(topk == t, axis=-1)), target.size


class Loss(ValidationMethod):
    """Average criterion value (reference: ``Loss`` validation method)."""

    name = "Loss"

    def __init__(self, criterion: Optional[Criterion] = None):
        if criterion is None:
            from bigdl_tpu.nn.criterion import CrossEntropyCriterion

            criterion = CrossEntropyCriterion()
        self.criterion = criterion

    def batch(self, output, target):
        n = output.shape[0]
        return self.criterion.forward(output, target) * n, n


class HitRatio(ValidationMethod):
    """HR@k for ranking (reference: ``ValidationMethod.scala`` HitRatio):
    output = scores over candidates, target row 0 is the positive item."""

    def __init__(self, k: int = 10, neg_num: int = 100):
        self.k = k
        self.neg = neg_num
        self.name = f"HitRatio@{k}"

    def batch(self, output, target):
        # output (B, n_candidates) scores; positive is column 0
        rank = jnp.sum(output > output[:, :1], axis=-1)
        return jnp.sum(rank < self.k), output.shape[0]


class NDCG(ValidationMethod):
    """NDCG@k with a single positive at column 0 (reference: ``NDCG``)."""

    def __init__(self, k: int = 10, neg_num: int = 100):
        self.k = k
        self.neg = neg_num
        self.name = f"NDCG@{k}"

    def batch(self, output, target):
        rank = jnp.sum(output > output[:, :1], axis=-1)
        gain = jnp.where(rank < self.k, 1.0 / jnp.log2(rank.astype(jnp.float32) + 2.0), 0.0)
        return jnp.sum(gain), output.shape[0]
