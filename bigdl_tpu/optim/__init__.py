from bigdl_tpu.optim.optim_method import (
    OptimMethod,
    SGD,
    Adam,
    ParallelAdam,
    Adagrad,
    Adadelta,
    Adamax,
    RMSprop,
    Ftrl,
    LarsSGD,
)
from bigdl_tpu.optim.schedules import (
    LearningRateSchedule,
    Default,
    Step,
    MultiStep,
    Poly,
    Exponential,
    NaturalExp,
    EpochDecay,
    EpochStep,
    EpochSchedule,
    Warmup,
    SequentialSchedule,
    Plateau,
)
from bigdl_tpu.optim.trigger import Trigger, TrainingState
from bigdl_tpu.optim.validation import (
    ValidationMethod,
    ValidationResult,
    Top1Accuracy,
    Top5Accuracy,
    TopKAccuracy,
    Loss,
    HitRatio,
    NDCG,
    MeanAveragePrecision,
    MeanAveragePrecisionObjectDetection,
    coco_detection_map,
    detection_average_precision,
    mask_iou,
)
from bigdl_tpu.optim.metrics import Metrics
from bigdl_tpu.optim.optimizer import Optimizer, LocalOptimizer, optimizer
from bigdl_tpu.optim.predictor import Evaluator, PredictionService, Predictor
from bigdl_tpu.optim.distri_optimizer import DistriOptimizer
