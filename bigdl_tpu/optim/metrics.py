"""Training metrics gauges.

Reference: ``DL/optim/Metrics.scala:31`` — named distributed gauges set
each iteration in ``DistriOptimizer.optimize`` ("computing time for each
node", "aggregate gradient time", ...), dumped via ``summary()`` (:103).
Here there are no Spark accumulators; gauges are host-side counters (one
process per host under SPMD), with the same names kept where they still
make sense for log parity.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Tuple


class Metrics:
    def __init__(self):
        self._lock = threading.Lock()
        self._scalars: Dict[str, float] = {}
        self._aggregates: Dict[str, Tuple[float, int]] = {}

    def set(self, name: str, value: float) -> None:
        with self._lock:
            self._scalars[name] = float(value)

    def add(self, name: str, value: float) -> None:
        with self._lock:
            total, n = self._aggregates.get(name, (0.0, 0))
            self._aggregates[name] = (total + float(value), n + 1)

    def get(self, name: str) -> float:
        with self._lock:
            if name in self._scalars:
                return self._scalars[name]
            total, n = self._aggregates.get(name, (0.0, 0))
            return total / max(1, n)

    def summary(self, unit_scale: float = 1.0) -> str:
        """Reference: ``Metrics.summary`` (:103)."""
        with self._lock:
            lines = ["========== Metrics Summary =========="]
            for k, v in self._scalars.items():
                lines.append(f"{k} : {v * unit_scale} s")
            for k, (total, n) in self._aggregates.items():
                lines.append(f"{k} : {total / max(1, n) * unit_scale} (avg over {n})")
            lines.append("=====================================")
            return "\n".join(lines)

    def reset(self) -> None:
        with self._lock:
            self._scalars.clear()
            self._aggregates.clear()
